"""Registration-level plans for the serving engine, with live re-planning.

:func:`plan_registration` rolls the per-decision plans for one
:class:`~raft_tpu.serve.engine.ServingEngine` registration into a
single immutable :class:`RegistrationPlan`: the resolved engine per
shape bucket, the cross-shard merge engine, the HBM placement tier
verdict, plus the traffic/corpus anchors the re-planner measures drift
against.

Re-planning (driven from the engine's maintenance tick) is generation-
style: :func:`needs_replan` watches the live inputs — corpus rows and
the engine's per-bucket batch-size counts — against hysteresis
thresholds; past a threshold the engine re-costs, and if any *decision*
changed it precompiles the new plan's warm buckets through the existing
ProgramCache and swaps the plan in one assignment (``epoch`` bumped,
``serve.plan_flips`` counted). A re-cost that lands on the same
decisions just refreshes the drift anchors (``serve.plan.recosts``) so
steady growth does not re-trigger every tick. Distinct compiled
programs stay bounded by plans × buckets: the resolved bucket mode
joins the ProgramKey, so only a bucket whose engine actually changed
recompiles.

Hysteresis knobs (see docs/planner.md):

* :data:`GROWTH_REPLAN_FACTOR` — corpus rows must grow (or shrink) by
  this factor past the planned anchor before a re-cost;
* :data:`TRAFFIC_MIN_SAMPLES` — batches observed before the dominant
  bucket is trusted (a cold histogram never flips a plan);
* :data:`WARM_BUCKETS` — how many of the most-trafficked buckets the
  flip precompiles.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

from raft_tpu.plan.planner import Plan, plan_cagra_mode, plan_merge_mode, plan_search_mode

#: corpus-size drift (x grow or /x shrink) that triggers a re-cost
GROWTH_REPLAN_FACTOR = 1.5
#: dispatched batches before the bucket histogram can drive a flip
TRAFFIC_MIN_SAMPLES = 16
#: top-N trafficked buckets precompiled on a plan flip
WARM_BUCKETS = 2

#: registration algos whose per-bucket search engine the planner picks
_MODE_PLANNED = ("ivf_flat", "ivf_pq", "cagra")


@dataclasses.dataclass(frozen=True)
class TrafficSnapshot:
    """What the engine measured since the last plan: per-bucket batch
    counts (the live batch-size histogram) and the rows/s EWMA."""

    bucket_counts: Tuple[Tuple[int, int], ...] = ()
    ewma_rows_per_s: float = 0.0

    @property
    def samples(self) -> int:
        return sum(n for _, n in self.bucket_counts)

    @property
    def dominant_bucket(self) -> int:
        best, best_n = 0, 0
        for b, n in self.bucket_counts:
            if n > best_n or (n == best_n and b < best):
                best, best_n = b, n
        return best

    def warm_buckets(self, limit: int = WARM_BUCKETS) -> Tuple[int, ...]:
        ranked = sorted(self.bucket_counts, key=lambda bn: (-bn[1], bn[0]))
        return tuple(sorted(b for b, _ in ranked[:limit]))


@dataclasses.dataclass(frozen=True)
class RegistrationPlan:
    """The active plan of one serving registration: every resolved
    decision plus the drift anchors it was costed against."""

    index_id: str
    algo: str
    epoch: int
    #: (bucket, resolved engine) pairs; empty when the registration's
    #: mode is caller-pinned (not "auto") or the algo has no engine pick
    bucket_modes: Tuple[Tuple[int, str], ...] = ()
    #: resolved cross-shard merge engine ("" when not sharded)
    merge_mode: str = ""
    #: HBM placement verdict label ("resident" | "tiered" |
    #: "tiered_sharded" | "" when unplanned)
    tier: str = ""
    #: corpus rows at planning time — the growth-hysteresis anchor
    corpus_rows: int = 0
    #: dominant shape bucket at planning time — the traffic anchor
    dominant_bucket: int = 0
    ewma_rows_per_s: float = 0.0
    #: traffic-chosen precompile set for the next flip
    warm_buckets: Tuple[int, ...] = ()
    #: the underlying costed decisions, for explain
    decisions: Tuple[Plan, ...] = ()

    def mode_for(self, bucket: int, default: str = "") -> str:
        for b, m in self.bucket_modes:
            if b == bucket:
                return m
        return default

    def same_decisions(self, other: "RegistrationPlan") -> bool:
        """True when flipping to ``other`` would change no dispatch
        decision (anchors may still differ — a re-cost, not a flip)."""
        return (
            self.bucket_modes == other.bucket_modes
            and self.merge_mode == other.merge_mode
            and self.tier == other.tier
            and self.warm_buckets == other.warm_buckets
        )

    def explain(self) -> str:
        head = (
            f"plan[{self.index_id}] epoch={self.epoch} algo={self.algo}"
            + (f" tier={self.tier}" if self.tier else "")
            + f" corpus_rows={self.corpus_rows}"
        )
        lines = [head]
        lines.append(
            f"  traffic: dominant_bucket={self.dominant_bucket} "
            f"ewma_rows_per_s={self.ewma_rows_per_s:.1f} "
            f"warm={self.warm_buckets or '()'}"
        )
        if self.bucket_modes:
            lines.append("  bucket modes: " + " ".join(
                f"{b}→{m}" for b, m in self.bucket_modes))
        if self.merge_mode:
            lines.append(f"  merge_mode: {self.merge_mode}")
        for p in self.decisions:
            lines.extend("  " + ln for ln in p.explain().splitlines())
        return "\n".join(lines)


def plan_registration(
    index_id: str,
    algo: str,
    *,
    buckets: Sequence[int],
    corpus_rows: int = 0,
    on_tpu: bool = False,
    fused_ok: bool = False,
    n_shards: int = 0,
    k: Optional[int] = None,
    tier: str = "",
    mode_pinned: bool = False,
    merge_pinned: bool = False,
    traffic: Optional[TrafficSnapshot] = None,
    epoch: int = 0,
) -> RegistrationPlan:
    """Cost one registration's full decision set.

    ``mode_pinned``/``merge_pinned`` mark decisions the caller fixed at
    registration ("auto" was not requested) — the planner records them
    as unplanned rather than second-guess an explicit pin. ``fused_ok``
    is the registration-time kernel-eligibility verdict for the fused
    engine (vmem_model-backed, computed by the call site)."""
    traffic = traffic or TrafficSnapshot()
    decisions = []
    bucket_modes: Tuple[Tuple[int, str], ...] = ()
    if algo in _MODE_PLANNED and not mode_pinned:
        modes = []
        for b in buckets:
            if algo == "cagra":
                p = plan_cagra_mode(int(b), on_tpu=on_tpu, fused_ok=fused_ok)
            else:
                p = plan_search_mode(algo, int(b), on_tpu=on_tpu, fused_ok=fused_ok)
            modes.append((int(b), p.choice))
            decisions.append(p)
        bucket_modes = tuple(modes)
    merge = ""
    if n_shards and not merge_pinned:
        p = plan_merge_mode(n_shards, k)
        merge = p.choice
        decisions.append(p)
    return RegistrationPlan(
        index_id=index_id,
        algo=algo,
        epoch=epoch,
        bucket_modes=bucket_modes,
        merge_mode=merge,
        tier=tier,
        corpus_rows=int(corpus_rows),
        dominant_bucket=traffic.dominant_bucket,
        ewma_rows_per_s=traffic.ewma_rows_per_s,
        warm_buckets=traffic.warm_buckets(),
        decisions=tuple(decisions),
    )


def needs_replan(plan: RegistrationPlan, corpus_rows: int,
                 traffic: TrafficSnapshot) -> bool:
    """Hysteresis check: has the live state drifted far enough from the
    plan's anchors that a re-cost is warranted?"""
    anchor = max(plan.corpus_rows, 1)
    rows = max(int(corpus_rows), 1)
    if rows >= anchor * GROWTH_REPLAN_FACTOR or rows * GROWTH_REPLAN_FACTOR <= anchor:
        return True
    if traffic.samples >= TRAFFIC_MIN_SAMPLES:
        if traffic.dominant_bucket != plan.dominant_bucket:
            return True
        if plan.warm_buckets and traffic.warm_buckets() != plan.warm_buckets:
            return True
    return False


def traffic_from_counts(bucket_counts: Dict[int, int],
                        ewma_rows_per_s: float) -> TrafficSnapshot:
    """Snapshot the engine's mutable per-registration traffic state."""
    return TrafficSnapshot(
        bucket_counts=tuple(sorted(bucket_counts.items())),
        ewma_rows_per_s=float(ewma_rows_per_s),
    )
