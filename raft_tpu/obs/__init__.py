"""raft_tpu.obs — query-path observability: metrics registry,
device-sync-aware spans, and Perfetto/Chrome-trace export.

Facade re-exporting the pieces the instrumented layers use::

    from raft_tpu import obs

    with obs.span("ivf_pq.search", mode=mode) as sp:
        out = run(...)
        sp.sync(out)            # block_until_ready at span end
    if obs.is_enabled():
        obs.inc("ivf_pq.search.calls", mode=mode)

Disabled by default; enable with ``RAFT_TPU_OBS=1`` or
``obs.enable()``. See ``docs/observability.md`` for the metric/span
taxonomy and ``tools/obs_report.py`` for the artifact summarizer.
"""
from raft_tpu.obs.export import (
    chrome_trace,
    load_trace,
    validate_trace,
    write_metrics_jsonl,
    write_trace,
)
from raft_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    disable,
    enable,
    inc,
    is_enabled,
    observe,
    registry,
    set_gauge,
)
from raft_tpu.obs.request import (
    NULL_SCOPE,
    current_trace,
    iter_trace_spans,
    new_trace_id,
    trace_scope,
)
from raft_tpu.obs import recorder, timeseries
from raft_tpu.obs.recorder import FlightRecorder, list_bundles, load_bundle
from raft_tpu.obs.slo import SLO, SloStatus, SloTracker
from raft_tpu.obs.spans import Span, span, traced
from raft_tpu.obs.timeseries import (
    Anomaly,
    EwmaDetector,
    HistogramSeries,
    SeriesBank,
    TimeSeries,
    default_detectors,
)

__all__ = [
    "Anomaly",
    "DEFAULT_BUCKETS",
    "Counter",
    "EwmaDetector",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "HistogramSeries",
    "NULL_SCOPE",
    "Registry",
    "SLO",
    "SeriesBank",
    "SloStatus",
    "SloTracker",
    "Span",
    "TimeSeries",
    "chrome_trace",
    "current_trace",
    "default_detectors",
    "disable",
    "enable",
    "inc",
    "is_enabled",
    "iter_trace_spans",
    "list_bundles",
    "load_bundle",
    "load_trace",
    "new_trace_id",
    "observe",
    "recorder",
    "registry",
    "set_gauge",
    "span",
    "timeseries",
    "trace_scope",
    "traced",
    "validate_trace",
    "write_metrics_jsonl",
    "write_trace",
]
