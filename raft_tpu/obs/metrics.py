"""Process-local metrics registry: counters, gauges, fixed-bucket
histograms, and the span buffer the query-path instrumentation reports
into.

The reference ships recall/latency stats as first-class outputs and
wraps every nontrivial entry point in NVTX ranges; this is the
always-on analog for a serving stack: a thread-safe, process-local
registry the hot paths (``ivf_pq.search``, ``cagra.search``,
``brute_force``, ``cluster/kmeans``, ``parallel/comms``) write into,
dumpable as a dict, JSONL, or Prometheus text exposition.

Like :mod:`raft_tpu.core.tracing` (env ``RAFT_TPU_TRACING``) the whole
subsystem is gated on one process-wide flag — env ``RAFT_TPU_OBS``,
**default off** — and the disabled path allocates nothing: every
recording helper checks :func:`is_enabled` first and returns before any
metric object, label tuple, or span record is created. Instrumented
call sites keep overhead unmeasurable (<1%) by guarding whole blocks
with ``if obs.is_enabled():``.

Metric identity is ``(kind, name, sorted labels)``; names are
dot-separated (``ivf_pq.search.calls``), labels are ``str -> str``
pairs (``mode="fused"``). The Prometheus dump sanitizes names to the
exposition charset; the dict/JSONL dumps keep them verbatim.
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from raft_tpu.utils import lockcheck

_TRUTHY = ("1", "true", "on", "yes")

_enabled = os.environ.get("RAFT_TPU_OBS", "0").strip().lower() in _TRUTHY


def enable(flag: bool = True) -> None:
    """Turn observability on/off process-wide (``RAFT_TPU_OBS`` analog)."""
    global _enabled
    _enabled = bool(flag)


def disable() -> None:
    enable(False)


def is_enabled() -> bool:
    return _enabled


#: default histogram buckets for millisecond timings (upper bounds)
DEFAULT_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
)

LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, Any]) -> LabelsKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


@lockcheck.guarded_fields
class Counter:
    """Monotonically increasing value (``prometheus counter`` semantics)."""

    __slots__ = ("name", "labels", "value", "_lock")
    kind = "counter"

    def __init__(self, name: str, labels: LabelsKey, lock: threading.RLock):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self._lock = lock

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self.value += value


@lockcheck.guarded_fields
class Gauge:
    """Last-write-wins value."""

    __slots__ = ("name", "labels", "value", "_lock")
    kind = "gauge"

    def __init__(self, name: str, labels: LabelsKey, lock: threading.RLock):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self.value += value


@lockcheck.guarded_fields
class Histogram:
    """Fixed-bucket histogram: ``buckets`` are sorted upper bounds; one
    implicit +Inf bucket catches the tail. Tracks sum and count like the
    Prometheus histogram type.

    ``observe(value, trace_id=...)`` additionally keeps one **exemplar**
    per bucket — the worst (largest) value seen with a trace attached —
    so a tail bucket resolves to a concrete request trace instead of an
    anonymous count (the Prometheus/OpenMetrics exemplar idea, but
    max-retaining rather than last-write, because the question the
    serving path asks is "which request made p99").
    """

    __slots__ = (
        "name", "labels", "buckets", "counts", "sum", "count",
        "exemplars", "_lock",
    )
    kind = "histogram"

    def __init__(
        self,
        name: str,
        labels: LabelsKey,
        lock: threading.RLock,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        #: bucket index -> (value, trace_id); worst value per bucket wins
        self.exemplars: Dict[int, Tuple[float, str]] = {}
        self._lock = lock

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        value = float(value)
        with self._lock:
            bi = bisect.bisect_left(self.buckets, value)
            self.counts[bi] += 1
            self.sum += value
            self.count += 1
            if trace_id:
                prev = self.exemplars.get(bi)
                if prev is None or value > prev[0]:
                    self.exemplars[bi] = (value, trace_id)

    def exemplar_rows(self) -> List[Dict[str, Any]]:
        """Exemplars as dicts, largest value first (dump/report shape)."""
        with self._lock:
            items = sorted(
                self.exemplars.items(), key=lambda kv: kv[1][0], reverse=True
            )
        return [
            {"bucket": bi, "value": v, "trace_id": t} for bi, (v, t) in items
        ]


@lockcheck.guarded_fields
class Registry:
    """Thread-safe metric + span store. One process-wide default lives in
    this module (:func:`registry`); tests may construct their own."""

    def __init__(self, max_spans: int = 200_000):
        # one shared (tracked) RLock for the registry and every
        # instrument it hands out; a leaf in lock_order.toml — nothing
        # may be acquired under it
        self._lock = lockcheck.tracked(threading.RLock(), "obs.registry")
        self._metrics: Dict[Tuple[str, str, LabelsKey], Any] = {}
        self._spans: List[Dict[str, Any]] = []
        self._t0 = time.perf_counter()
        self.max_spans = max_spans
        self.spans_dropped = 0

    # -- get-or-create ----------------------------------------------------

    def _get(self, cls, name: str, labels: Dict[str, Any], **kwargs):
        key = (cls.kind, name, _labels_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, key[2], self._lock, **kwargs)
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(
        self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS, **labels
    ) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    # -- enabled-gated recording (the hot-path API) -----------------------

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        if not _enabled:
            return
        self.counter(name, **labels).inc(value)

    def set(self, name: str, value: float, **labels) -> None:
        if not _enabled:
            return
        self.gauge(name, **labels).set(value)

    def observe(
        self, name: str, value: float, trace_id: Optional[str] = None, **labels
    ) -> None:
        if not _enabled:
            return
        self.histogram(name, **labels).observe(value, trace_id=trace_id)

    # -- sampling ----------------------------------------------------------

    def sample(
        self, prefixes: Optional[Sequence[str]] = None
    ) -> List[Tuple[str, str, LabelsKey, Any]]:
        """One consistent snapshot of instrument values for time-series
        retention (:mod:`raft_tpu.obs.timeseries`): ``(kind, name,
        labels, payload)`` rows, where payload is the value for
        counters/gauges and ``(buckets, counts, sum, count)`` for
        histograms. ``prefixes`` filters by name prefix; like the dump
        paths, the whole scan runs under the shared instrument lock so
        a row can never carry a torn sum/count pair."""
        pref = tuple(prefixes) if prefixes else None
        out: List[Tuple[str, str, LabelsKey, Any]] = []
        with self._lock:
            for m in self._metrics.values():
                if pref is not None and not m.name.startswith(pref):
                    continue
                if m.kind == "histogram":
                    payload: Any = (
                        m.buckets, tuple(m.counts), m.sum, m.count
                    )
                else:
                    payload = m.value
                out.append((m.kind, m.name, m.labels, payload))
        return out

    # -- spans ------------------------------------------------------------

    def now_us(self) -> float:
        """Microseconds since this registry's epoch (the trace clock)."""
        return (time.perf_counter() - self._t0) * 1e6

    def record_span(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        tid: int,
        depth: int,
        args: Optional[Dict[str, Any]] = None,
        trace: Sequence[str] = (),
    ) -> None:
        rec = {
            "name": name,
            "ts_us": ts_us,
            "dur_us": dur_us,
            "tid": tid,
            "depth": depth,
            "args": args or {},
        }
        if trace:
            rec["trace"] = list(trace)
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.spans_dropped += 1
                # visible drop signal: the plain attribute is easy to miss
                # in dashboards; the counter rides every normal dump. The
                # registry lock is an RLock, so self.inc under it is safe.
                self.inc("obs.spans_dropped")
                return
            self._spans.append(rec)

    def spans(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            snap = list(self._spans)
        if name is None:
            return snap
        return [s for s in snap if s["name"] == name]

    # -- dumps ------------------------------------------------------------

    @staticmethod
    def _fmt_key(name: str, labels: LabelsKey) -> str:
        if not labels:
            return name
        inner = ",".join(f'{k}="{v}"' for k, v in labels)
        return f"{name}{{{inner}}}"

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        # Every instrument shares this registry's RLock, so reading their
        # fields here IS the writers' critical section — snapshotting the
        # metric list and formatting off-lock would copy counts/sum/count
        # mid-observe (torn histogram totals).
        with self._lock:
            for m in self._metrics.values():
                key = self._fmt_key(m.name, m.labels)
                if m.kind == "histogram":
                    h = {
                        "buckets": list(m.buckets),
                        "counts": list(m.counts),
                        "sum": m.sum,
                        "count": m.count,
                    }
                    if m.exemplars:
                        h["exemplars"] = m.exemplar_rows()
                    out["histograms"][key] = h
                else:
                    out[m.kind + "s"][key] = m.value
            out["n_spans"] = len(self._spans)
            out["spans_dropped"] = self.spans_dropped
        return out

    def dump_jsonl(self, stream) -> None:
        """One JSON object per line: every metric, then every span — a
        self-contained snapshot ``tools/obs_report.py`` can summarize."""
        recs: List[Dict[str, Any]] = []
        # build the records under the shared instrument lock (see
        # as_dict); only the stream writes happen off-lock
        with self._lock:
            for m in self._metrics.values():
                rec: Dict[str, Any] = {
                    "kind": m.kind,
                    "name": m.name,
                    "labels": dict(m.labels),
                }
                if m.kind == "histogram":
                    rec.update(
                        buckets=list(m.buckets), counts=list(m.counts),
                        sum=m.sum, count=m.count,
                    )
                    if m.exemplars:
                        rec["exemplars"] = m.exemplar_rows()
                else:
                    rec["value"] = m.value
                recs.append(rec)
            spans = list(self._spans)
        for rec in recs:
            stream.write(json.dumps(rec) + "\n")
        for s in spans:
            stream.write(json.dumps({"kind": "span", **s}) + "\n")

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (the ``/metrics`` payload)."""
        lines: List[str] = []
        seen_type: set = set()
        # string formatting is cheap; holding the shared instrument lock
        # across it buys consistent bucket/sum/count triples (see as_dict)
        with self._lock:
            for m in self._metrics.values():
                pname = _prom_name(m.name)
                if pname not in seen_type:
                    seen_type.add(pname)
                    lines.append(f"# TYPE {pname} {m.kind}")
                if m.kind == "histogram":
                    cum = 0
                    for ub, c in zip(m.buckets, m.counts):
                        cum += c
                        lines.append(
                            self._fmt_key(
                                pname + "_bucket", m.labels + (("le", _fmt_float(ub)),)
                            )
                            + f" {cum}"
                        )
                    cum += m.counts[-1]
                    lines.append(
                        self._fmt_key(pname + "_bucket", m.labels + (("le", "+Inf"),))
                        + f" {cum}"
                    )
                    lines.append(self._fmt_key(pname + "_sum", m.labels) + f" {_fmt_float(m.sum)}")
                    lines.append(self._fmt_key(pname + "_count", m.labels) + f" {m.count}")
                else:
                    lines.append(self._fmt_key(pname, m.labels) + f" {_fmt_float(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._spans.clear()
            self.spans_dropped = 0
            self._t0 = time.perf_counter()


def _prom_name(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


def _fmt_float(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


_default = Registry()


def registry() -> Registry:
    """The process-wide default registry."""
    return _default


# module-level conveniences bound to the default registry
def inc(name: str, value: float = 1.0, **labels) -> None:
    if not _enabled:
        return
    _default.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels) -> None:
    if not _enabled:
        return
    _default.set(name, value, **labels)


def observe(
    name: str, value: float, trace_id: Optional[str] = None, **labels
) -> None:
    if not _enabled:
        return
    _default.observe(name, value, trace_id=trace_id, **labels)
