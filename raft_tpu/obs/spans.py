"""Nested, device-sync-aware wall-clock spans.

A :func:`span` is a host-side timing scope recorded into the
:mod:`raft_tpu.obs.metrics` registry and exportable as Chrome-trace
``trace_events`` (:mod:`raft_tpu.obs.export`). Two properties matter on
TPU:

* **Sync-aware.** JAX dispatch is asynchronous: a naive
  ``perf_counter`` delta around a jitted call measures *enqueue* time,
  not compute (the dispatch-dominated bug bench.py's ``_hw_context``
  once had, and the graft-lint ``unsynced-timing`` rule now flags).
  Registering the op's outputs with :meth:`Span.sync` makes the span
  end call ``jax.block_until_ready`` on them first, so the recorded
  duration covers the device work.

* **Zero-cost when disabled.** With ``RAFT_TPU_OBS`` off (the default)
  ``span()`` yields a shared null object and records nothing — no
  timestamps, no allocation beyond the generator frame.

Spans nest by wall-clock containment per thread (the Perfetto/Chrome
``ph: "X"`` convention); ``depth`` is tracked explicitly so reporters
need not re-derive it.
"""
from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Any, Iterator, Optional

from raft_tpu.obs import metrics, request

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class Span:
    """Mutable scope handle yielded by :func:`span`."""

    __slots__ = ("name", "args", "_sync")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args
        self._sync: list = []

    def set(self, **kv) -> None:
        """Attach/overwrite trace args (visible in Perfetto's arg pane)."""
        self.args.update(kv)

    def sync(self, outputs):
        """Register ``outputs`` (any pytree of jax arrays) to be
        ``block_until_ready``-ed at span end; returns ``outputs`` so call
        sites can wrap a return value in place."""
        self._sync.append(outputs)
        return outputs


class _NullSpan:
    """Disabled-path stand-in: same surface, does nothing."""

    __slots__ = ()

    def set(self, **kv) -> None:
        pass

    def sync(self, outputs):
        return outputs


_NULL = _NullSpan()


@contextlib.contextmanager
def span(name: str, **args) -> Iterator[Any]:
    """Record a nested wall-clock span named ``name`` into the default
    registry. ``args`` become Chrome-trace args. Use ``sp.sync(out)`` on
    the yielded handle to include device completion in the duration."""
    if not metrics.is_enabled():
        yield _NULL
        return
    reg = metrics.registry()
    st = _stack()
    depth = len(st)
    s = Span(name, dict(args))
    st.append(s)
    ts = reg.now_us()
    t0 = time.perf_counter()
    try:
        yield s
    finally:
        if s._sync:
            import jax

            try:
                jax.block_until_ready(s._sync)
            except Exception:  # graft-lint: ignore[silent-except] — timing must never mask the real error
                pass
        dur = (time.perf_counter() - t0) * 1e6
        if st and st[-1] is s:
            st.pop()
        reg.record_span(
            name, ts, dur, threading.get_ident(), depth, s.args,
            trace=request.current_trace(),
        )


def traced(name: Optional[str] = None, sync_result: bool = True):
    """Decorator form: wrap a function in a span, syncing on its return
    value by default (the ``annotate`` analog for wall-clock spans)."""

    def deco(fn):
        label = name or f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not metrics.is_enabled():
                return fn(*a, **kw)
            with span(label) as s:
                out = fn(*a, **kw)
                if sync_result:
                    s.sync(out)
                return out

        return wrapper

    return deco
