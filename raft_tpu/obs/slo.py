"""Service-level objectives: sliding error budgets and multi-window
burn-rate alerting for the serving path.

The model is the SRE-workbook one. An :class:`SLO` declares, per
registered index, a latency objective ("``target`` of requests complete
within ``latency_ms``") and/or an availability objective (a request that
errors or is shed counts against the same budget). The error budget over
``window_s`` is the ``1 - target`` fraction of requests allowed to miss;
the **burn rate** over a window is::

    burn = bad_fraction(window) / (1 - target)

so burn 1.0 spends the budget exactly at sustainable pace and burn 14
exhausts a 30-day budget in ~2 days. Alerting is **multi-window**: the
alert fires only when both the fast and the slow window burn above
``burn_threshold`` (the fast window gives responsiveness, the slow
window rejects blips), and clears as soon as the fast window recovers —
the standard shape that pages quickly on real incidents without flapping
on a single slow batch.

Trackers are clock-injectable (the serving tests drive them with the
same virtual clock as :class:`raft_tpu.serve.batcher.MicroBatcher`) and
feed the shared obs registry: ``slo.burn_rate{index_id,window}``,
``slo.budget_remaining{index_id}``, ``slo.requests{index_id,outcome}``
and ``slo.alerts{index_id,transition}``. ``ServingEngine.health()``
surfaces :meth:`SloTracker.evaluate` snapshots per index.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional, Tuple

from raft_tpu.core.errors import expects
from raft_tpu.obs import metrics, recorder
from raft_tpu.utils import lockcheck

#: hard cap on retained window events per tracker (memory backstop; the
#: window itself is time-pruned on every record)
_MAX_EVENTS = 262_144


@dataclasses.dataclass(frozen=True)
class SLO:
    """Declared objective for one serving index."""

    index_id: str
    #: per-request latency threshold; ``None`` = availability-only SLO
    latency_ms: Optional[float] = None
    #: fraction of requests that must be good (0 < target < 1)
    target: float = 0.999
    #: error-budget accounting window (seconds)
    window_s: float = 3600.0
    #: fast burn-rate window — responsiveness
    fast_window_s: float = 60.0
    #: slow burn-rate window — blip rejection
    slow_window_s: float = 300.0
    #: both windows must burn at >= this multiple of budget rate to fire
    burn_threshold: float = 10.0

    def __post_init__(self):
        expects(0.0 < self.target < 1.0, "SLO target must be in (0, 1), got %r",
                self.target)
        expects(self.latency_ms is None or self.latency_ms > 0.0,
                "SLO latency_ms must be positive, got %r", self.latency_ms)
        expects(0.0 < self.fast_window_s <= self.slow_window_s <= self.window_s,
                "SLO windows must satisfy fast <= slow <= budget (got %r/%r/%r)",
                self.fast_window_s, self.slow_window_s, self.window_s)
        expects(self.burn_threshold > 0.0,
                "SLO burn_threshold must be positive, got %r",
                self.burn_threshold)


@dataclasses.dataclass(frozen=True)
class SloStatus:
    """One :meth:`SloTracker.evaluate` snapshot."""

    index_id: str
    target: float
    latency_ms: Optional[float]
    requests: int          # events inside window_s
    bad: int               # budget-consuming events inside window_s
    bad_fraction: float
    budget_remaining: float  # 1.0 = untouched, 0.0 = spent, <0 = overspent
    burn_fast: float
    burn_slow: float
    burn_threshold: float
    alerting: bool
    alerts_fired: int
    alerts_cleared: int

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@lockcheck.guarded_fields
class SloTracker:
    """Sliding-window good/bad accounting + burn-rate alert state for
    one :class:`SLO`. Thread-safe; metric emission happens outside the
    tracker lock (see ``lock_order.toml``: ``obs.slo`` is edge-free)."""

    def __init__(self, slo: SLO, clock: Callable[[], float] = time.monotonic):
        self.slo = slo
        self._clock = clock
        self._lock = lockcheck.tracked(threading.RLock(), "obs.slo")
        # (t, bad) sliding window: time-pruned each record; maxlen is the
        # memory backstop under pathological rates — dropping the OLDEST
        # event is the window semantics anyway, just earlier
        self._events: Deque[Tuple[float, bool]] = deque(maxlen=_MAX_EVENTS)
        self._alerting = False
        self._fired = 0
        self._cleared = 0

    # -- recording --------------------------------------------------------

    def record(self, latency_ms: Optional[float] = None, ok: bool = True) -> None:
        """Account one request: ``ok=False`` (error/shed) always consumes
        budget; with a latency objective, a latency above the threshold
        consumes budget too. Re-evaluates alert state so transitions are
        observed at record time, not only when ``health()`` is polled."""
        bad = (not ok) or (
            self.slo.latency_ms is not None
            and latency_ms is not None
            and latency_ms > self.slo.latency_ms
        )
        now = self._clock()
        with self._lock:
            self._events.append((now, bad))
            self._prune(now)
        metrics.inc("slo.requests", index_id=self.slo.index_id,
                    outcome="bad" if bad else "good")
        self.evaluate()

    def _prune(self, now: float) -> None:
        horizon = now - self.slo.window_s
        ev = self._events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    def _burn(self, now: float, window_s: float) -> float:
        horizon = now - window_s
        n = bad = 0
        for t, b in reversed(self._events):
            if t < horizon:
                break
            n += 1
            bad += b
        if n == 0:
            return 0.0
        return (bad / n) / (1.0 - self.slo.target)

    # -- evaluation -------------------------------------------------------

    def evaluate(self) -> SloStatus:
        """Prune, compute burn rates, update alert state, emit gauges.

        Fire: both windows burning >= threshold. Clear: fast window back
        under threshold (slow may lag — that is the point)."""
        now = self._clock()
        slo = self.slo
        with self._lock:
            self._prune(now)
            n = len(self._events)
            bad = sum(1 for _, b in self._events if b)
            burn_fast = self._burn(now, slo.fast_window_s)
            burn_slow = self._burn(now, slo.slow_window_s)
            transition = None
            if not self._alerting and (
                burn_fast >= slo.burn_threshold and burn_slow >= slo.burn_threshold
            ):
                self._alerting = True
                self._fired += 1
                transition = "fire"
            elif self._alerting and burn_fast < slo.burn_threshold:
                self._alerting = False
                self._cleared += 1
                transition = "clear"
            status = SloStatus(
                index_id=slo.index_id,
                target=slo.target,
                latency_ms=slo.latency_ms,
                requests=n,
                bad=bad,
                bad_fraction=(bad / n) if n else 0.0,
                budget_remaining=(
                    1.0 - ((bad / n) / (1.0 - slo.target)) if n else 1.0
                ),
                burn_fast=burn_fast,
                burn_slow=burn_slow,
                burn_threshold=slo.burn_threshold,
                alerting=self._alerting,
                alerts_fired=self._fired,
                alerts_cleared=self._cleared,
            )
        # emit OUTSIDE the tracker lock: obs.slo must stay edge-free
        if metrics.is_enabled():
            metrics.set_gauge("slo.burn_rate", burn_fast,
                              index_id=slo.index_id, window="fast")
            metrics.set_gauge("slo.burn_rate", burn_slow,
                              index_id=slo.index_id, window="slow")
            metrics.set_gauge("slo.budget_remaining", status.budget_remaining,
                              index_id=slo.index_id)
            if transition is not None:
                metrics.inc("slo.alerts", index_id=slo.index_id,
                            transition=transition)
                # flight-recorder trigger: rides the same outside-lock
                # emission point, so obs.recorder (like obs.registry
                # here) is never acquired under obs.slo
                recorder.note_slo_transition(
                    slo.index_id, transition, burn_fast, burn_slow
                )
        return status
