"""Request-scoped trace identity for the serving path.

A trace ID is minted once per request at ``ServingEngine.submit()`` and
rides along through batcher enqueue -> micro-batch dispatch -> degraded
sharded search -> tiered host fetch -> refine. Everything that happens on
a thread while a :func:`trace_scope` is active tags its spans with the
active trace IDs (a micro-batch carries one ID per batched request), so
one slow request can be followed across threads in the Perfetto export
(flow events, :mod:`raft_tpu.obs.export`) and resolved from histogram
exemplars (:mod:`raft_tpu.obs.metrics`).

Gate discipline matches the rest of :mod:`raft_tpu.obs`: with the
``RAFT_TPU_OBS`` gate off, :func:`new_trace_id` returns ``""`` and no
thread-local state, tuple, or ID string is ever allocated — the serving
engine stores the empty string it already had and ``ServeResult`` stays
bit-identical to the un-instrumented build.

Trace IDs are process-local (``t`` + a monotonic counter in hex): they
identify a request within one registry epoch, which is all the offline
tooling (``tools/obs_report.py`` tail attribution) needs.
"""
from __future__ import annotations

import itertools
import threading
from typing import Iterator, Sequence, Tuple

from raft_tpu.obs import metrics

_counter = itertools.count(1)
_tls = threading.local()


def new_trace_id() -> str:
    """Mint a fresh trace ID, or ``""`` when obs is disabled."""
    if not metrics.is_enabled():
        return ""
    return f"t{next(_counter):08x}"


def current_trace() -> Tuple[str, ...]:
    """Trace IDs active on this thread (``()`` outside any scope)."""
    return getattr(_tls, "trace", ())


class _NullScope:
    """Reusable no-op scope for the disabled gate — no per-dispatch
    generator frame, no state."""

    __slots__ = ()

    def __enter__(self) -> Tuple[str, ...]:
        return ()

    def __exit__(self, *exc) -> bool:
        return False


NULL_SCOPE = _NullScope()


class trace_scope:
    """Bind ``trace_ids`` to the current thread for the ``with`` body.

    Spans recorded inside the scope (on this thread) carry the IDs; the
    previous binding is restored on exit, so scopes nest with inner-wins
    semantics. Empty / falsy IDs are dropped; an all-empty scope still
    clears any outer binding, which is what a dispatch of untraced
    requests wants.
    """

    __slots__ = ("_ids", "_prev")

    def __init__(self, trace_ids: Sequence[str] = ()):
        self._ids = tuple(t for t in trace_ids if t)
        self._prev: Tuple[str, ...] = ()

    def __enter__(self) -> Tuple[str, ...]:
        self._prev = getattr(_tls, "trace", ())
        _tls.trace = self._ids
        return self._ids

    def __exit__(self, *exc) -> bool:
        _tls.trace = self._prev
        return False


def iter_trace_spans(reg: metrics.Registry, trace_id: str) -> Iterator[dict]:
    """Yield every span in ``reg`` tagged with ``trace_id`` (ts order)."""
    matched = [s for s in reg.spans() if trace_id in (s.get("trace") or ())]
    matched.sort(key=lambda s: s["ts_us"])
    return iter(matched)
