"""Flight recorder: an always-on "black box" for the serving stack.

A :class:`FlightRecorder` holds the last ``window_s`` seconds of
evidence in bounded memory — metric time series (a
:class:`~raft_tpu.obs.timeseries.SeriesBank` sampled on the maintenance
tick, rate-limited to ``sample_interval_s``, plus one final at-trigger
sample in every dump), and the incident event stream (anomalies, fault-seam
firings, SLO alert transitions, breaker trips, plan flips, compactor
worker deaths). On a **trigger** it writes one atomic, CRC-framed
diagnostic bundle capturing everything a post-mortem needs:

* the trigger cause and context, and the retained event stream;
* every retained time series with its points (windowed stats are
  recomputed by the reader — ``tools/bundle_report.py``);
* the full registry snapshot, and the slowest exemplar traces with
  their complete span chains (``serve.queue -> serve.dispatch -> ...``);
* ``plan_explain()`` per registered index and ``health()`` for every
  attached engine / replica group (including the cluster aggregate);
* lockcheck witness state and a config/env fingerprint.

Bundles ride :func:`raft_tpu.core.serialize.atomic_write` and the v4
checksummed envelope (kind ``obs_bundle``), so a crash mid-dump — the
``recorder.dump`` chaos seam exists to prove this — leaves either no
file or a CRC-valid one, never a torn bundle.

Locking contract (``lock_order.toml``): ``obs.recorder`` is an
edge-free leaf. The registry snapshot is taken *before* the lock is
entered, bundle assembly (``health()``, ``plan_explain()``, file I/O)
runs after it is released, and — critically — the ``note_*`` hook path
acquires **no lock at all**: events land in a bounded ``deque``
(GIL-atomic appends), because fault seams fire inside other
subsystems' critical sections (e.g. ``wal.append`` under the writer
lock) and the recorder must never insert itself into their ordering.
For the same reason a fault trigger only *latches* a pending dump
(single-slot, last-wins) that the next :meth:`FlightRecorder.tick`
drains; SLO/breaker/plan-flip/worker-death triggers dump inline — their
hook sites sit exactly where registry emission already happens, i.e.
contractually outside every tracked lock.

Gate discipline mirrors :mod:`raft_tpu.obs.metrics`: with
``RAFT_TPU_OBS`` off every entry point returns before allocating, so an
installed recorder costs nothing and gates-off serving stays
bit-identical.
"""
from __future__ import annotations

import io
import json
import os
import platform
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from raft_tpu.core import serialize
from raft_tpu.obs import metrics, request, timeseries
from raft_tpu.utils import lockcheck

BUNDLE_KIND = "obs_bundle"
BUNDLE_VERSION = 1
BUNDLE_SUFFIX = ".raftbundle"

#: trigger causes an auto-dumping recorder reacts to (``manual`` — an
#: explicit :func:`dump` call — is always allowed)
DEFAULT_TRIGGERS = frozenset(
    {"slo", "fault", "breaker", "plan_flip", "worker", "election", "fenced"}
)


@lockcheck.guarded_fields
class FlightRecorder:
    """Bounded black-box recorder over one metrics registry.

    Construction wires a :class:`~raft_tpu.obs.timeseries.SeriesBank`
    (sampled on :meth:`tick`, at most every ``sample_interval_s``
    seconds) and the stock drift detectors; engines and
    replica groups are :meth:`attach_engine`/:meth:`attach_group`-ed so
    bundles can capture their ``health()`` and plans. ``clock`` is
    injectable like the batcher's.
    """

    def __init__(
        self,
        out_dir: str,
        window_s: float = 60.0,
        capacity: int = 512,
        max_events: int = 2048,
        min_dump_interval_s: float = 5.0,
        sample_interval_s: float = 0.25,
        slow_traces: int = 5,
        triggers: Sequence[str] = DEFAULT_TRIGGERS,
        detectors: Optional[List[timeseries.EwmaDetector]] = None,
        tracked: Sequence[str] = timeseries.DEFAULT_TRACKED,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.out_dir = str(out_dir)
        self.window_s = float(window_s)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.sample_interval_s = float(sample_interval_s)
        self.slow_traces = int(slow_traces)
        self.triggers = frozenset(triggers)
        self.tracked = tuple(tracked)
        self._clock = clock
        self._lock = lockcheck.tracked(threading.RLock(), "obs.recorder")
        self._tls = threading.local()
        # lock-free state (see the module docstring's locking contract):
        # the bounded event ring — appended from arbitrary lock contexts
        # via the note_* hooks, GIL-atomic — and the single-slot pending
        # fault-trigger latch the next tick drains (last-wins)
        self._events: Deque[Dict[str, Any]] = deque(maxlen=int(max_events))
        self._pending: List[Optional[Tuple[str, Dict[str, Any], float]]] = [None]
        # lock-free last-sample stamp (GIL-atomic float, last-wins): the
        # interval check runs before the registry snapshot, which itself
        # must precede the recorder lock (edge-free leaf) — a racy
        # double-sample is benign, a lock here is not
        self._last_sample = -float("inf")
        # lock-guarded state (lock_order.toml [[guards]])
        self._bank = timeseries.SeriesBank(
            tracked=tracked, capacity=int(capacity), clock=clock
        )
        self._detectors = (
            detectors if detectors is not None else timeseries.default_detectors()
        )
        self._engines: List[Any] = []
        self._groups: List[Any] = []
        self._dumps: List[str] = []
        self._seq = 0
        self._last_dump_t: Optional[float] = None

    # -- wiring ------------------------------------------------------------

    def attach_engine(self, engine: Any) -> None:
        """Bundle this engine's ``health()`` + per-index plans."""
        with self._lock:
            self._engines.append(engine)

    def attach_group(self, group: Any) -> None:
        """Bundle this replica group's ``health()`` (cluster snapshot)."""
        with self._lock:
            self._groups.append(group)

    # -- the event stream (lock-free; callable under any lock) -------------

    def _record(self, kind: str, **data) -> None:
        if not metrics.is_enabled():
            return
        data["t"] = self._clock()
        data["kind"] = kind
        self._events.append(data)

    def events(self, window_s: Optional[float] = None) -> List[Dict[str, Any]]:
        """Retained events, newest last; ``window_s`` filters by age."""
        evs = list(self._events)
        if window_s is None:
            return evs
        horizon = self._clock() - window_s
        return [e for e in evs if e["t"] >= horizon]

    def note_fault(self, point: str, kind: str) -> None:
        """A fault seam fired. Seams fire inside other subsystems'
        critical sections, so this path must not dump (or lock) inline:
        error faults latch a pending dump for the next tick. Latency
        faults are perf noise, not incidents — event only. The
        recorder's own ``recorder.dump`` seam never re-triggers."""
        self._record("fault", point=point, fault_kind=kind)
        if (
            point != "recorder.dump"
            and kind != "latency"
            and "fault" in self.triggers
            and metrics.is_enabled()
            and self._pending[0] is None
        ):
            self._pending[0] = (
                "fault", {"point": point, "fault_kind": kind}, self._clock()
            )

    def note_slo_transition(
        self,
        index_id: str,
        transition: str,
        burn_fast: Optional[float] = None,
        burn_slow: Optional[float] = None,
    ) -> Optional[str]:
        """An SLO alert fired or cleared (called by
        :meth:`~raft_tpu.obs.slo.SloTracker.evaluate`, outside its
        lock). ``fire`` transitions trigger a dump."""
        self._record(
            "slo", index_id=index_id, transition=transition,
            burn_fast=burn_fast, burn_slow=burn_slow,
        )
        if transition == "fire":
            return self._trigger("slo", {"index_id": index_id})
        return None

    def note_breaker(self, target: str, to: str) -> Optional[str]:
        """A circuit breaker changed state; ``open`` triggers a dump."""
        self._record("breaker", target=target, to=to)
        if to == "open":
            return self._trigger("breaker", {"target": target})
        return None

    def note_plan_flip(self, index_id: str, epoch: int) -> Optional[str]:
        """The planner swapped an index's plan."""
        self._record("plan_flip", index_id=index_id, epoch=epoch)
        return self._trigger("plan_flip", {"index_id": index_id, "epoch": epoch})

    def note_worker_death(self, index: str) -> Optional[str]:
        """A compactor worker died and was restarted by the watchdog."""
        self._record("worker_death", index=index)
        return self._trigger("worker", {"index": index})

    def note_election(
        self, index_id: str, epoch: int, leader: str, reason: str
    ) -> Optional[str]:
        """The control plane elected a new leader (called by
        :meth:`~raft_tpu.replica.control.ControlPlane.tick` with no
        tracked lock held — elections run on the maintenance driver).
        A leader change is always an incident worth a bundle."""
        self._record(
            "election", index_id=index_id, epoch=epoch, leader=leader,
            reason=reason,
        )
        return self._trigger(
            "election",
            {"index_id": index_id, "epoch": epoch, "leader": leader,
             "reason": reason},
        )

    def note_fenced(self, follower: str, epoch: int, fence_epoch: int) -> Optional[str]:
        """A follower rejected a stale-epoch frame — evidence a deposed
        leader is still shipping (called from ``Follower.apply``,
        contractually outside every tracked lock)."""
        self._record(
            "fenced", follower=follower, epoch=epoch, fence_epoch=fence_epoch
        )
        return self._trigger(
            "fenced",
            {"follower": follower, "epoch": epoch, "fence_epoch": fence_epoch},
        )

    def note_scale(self, group: str, direction: str, n_replicas: int) -> None:
        """The autoscaler resized a replica group (event only — scaling
        is routine capacity management, not an incident)."""
        self._record(
            "scale", group=group, direction=direction, n_replicas=n_replicas
        )

    def note_anomaly(self, anomaly: timeseries.Anomaly) -> None:
        """A drift detector fired (event only — detectors inform, the
        SLO/fault/breaker machinery decides)."""
        self._record("anomaly", **anomaly.as_dict())

    def _trigger(self, cause: str, ctx: Dict[str, Any]) -> Optional[str]:
        if cause not in self.triggers or not metrics.is_enabled():
            return None
        return self.dump(cause=cause, ctx=ctx, _auto=True)

    # -- sampling ----------------------------------------------------------

    def tick(self, reg: Optional[metrics.Registry] = None) -> List[timeseries.Anomaly]:
        """One recorder tick (driven from ``ServingEngine.
        maintenance_tick``, or any scheduler): sample the registry into
        the series bank, run the drift detectors, and drain a pending
        fault-triggered dump. Sampling is rate-limited to
        ``sample_interval_s`` — the maintenance tick fires every ~10 ms
        but a 60 s window needs second-scale resolution, and the
        registry scan holds the shared instrument lock the serving hot
        path contends on. The latch drain runs on *every* tick so a
        fault-triggered dump stays prompt. Returns the anomalies
        detected."""
        if not metrics.is_enabled():
            return []
        if reg is None:
            reg = metrics.registry()
        now = self._clock()
        anomalies: List[timeseries.Anomaly] = []
        if now - self._last_sample >= self.sample_interval_s:
            self._last_sample = now
            # snapshot BEFORE taking the recorder lock: obs.recorder must
            # never be held while obs.registry is acquired (edge-free leaf)
            rows = reg.sample(self.tracked)
            with self._lock:
                self._bank.ingest(rows, now)
                for d in self._detectors:
                    anomalies.extend(d.check(self._bank, now))
            for a in anomalies:
                self.note_anomaly(a)
                metrics.inc(
                    "obs.anomaly", signal=a.signal, index_id=a.index_id
                )
        pending = self._pending[0]
        if pending is not None:
            self._pending[0] = None
            cause, ctx, t = pending
            ctx = dict(ctx)
            ctx["latched_t"] = t
            self.dump(cause=cause, ctx=ctx, _auto=True)
        return anomalies

    # -- dumping -----------------------------------------------------------

    def dumps(self) -> List[str]:
        """Paths of every bundle this recorder has written."""
        with self._lock:
            return list(self._dumps)

    def dump(
        self,
        cause: str = "manual",
        ctx: Optional[Dict[str, Any]] = None,
        _auto: bool = False,
    ) -> Optional[str]:
        """Write one diagnostic bundle; returns its path, or None when
        gated off, debounced (auto triggers only), re-entered (bundle
        assembly polls ``health()``, which can re-evaluate SLOs), or
        failed (counted in ``recorder.dump_failures{kind}``)."""
        if not metrics.is_enabled():
            return None
        if getattr(self._tls, "in_dump", False):
            return None
        now = self._clock()
        # one final at-trigger sample so the bundle's series always
        # include the state at the incident, whatever the rate-limited
        # sampler cadence (registry snapshot before the recorder lock —
        # edge-free leaf; discarded if the dump is debounced)
        rows = metrics.registry().sample(self.tracked)
        with self._lock:
            if (
                _auto
                and self._last_dump_t is not None
                and (now - self._last_dump_t) < self.min_dump_interval_s
            ):
                return None
            self._last_dump_t = now
            self._seq += 1
            seq = self._seq
            self._bank.ingest(rows, now)
            self._last_sample = now
            series = self._bank.as_dict()
            engines = tuple(self._engines)
            groups = tuple(self._groups)
        events = self.events(window_s=self.window_s)
        self._tls.in_dump = True
        try:
            body = self._build_body(cause, dict(ctx or {}), now, events,
                                    series, engines, groups)
            payload = json.dumps(body, default=str).encode("utf-8")
            buf = io.BytesIO()
            serialize.save_stream(buf, BUNDLE_KIND, BUNDLE_VERSION, payload)
            blob = buf.getvalue()
            path = os.path.join(
                self.out_dir, f"bundle-{seq:04d}-{cause}{BUNDLE_SUFFIX}"
            )

            def _write(f, _blob=blob, _cause=cause):
                from raft_tpu.robust import faults

                half = len(_blob) // 2
                f.write(_blob[:half])
                # the chaos seam tests/test_recorder.py kills a dump at:
                # atomic_write must leave no bundle or a CRC-valid one
                faults.fire("recorder.dump", cause=_cause)
                f.write(_blob[half:])

            serialize.atomic_write(path, _write)
        except Exception as e:
            metrics.inc("recorder.dump_failures", kind=type(e).__name__)
            return None
        finally:
            self._tls.in_dump = False
        metrics.inc("recorder.dumps", cause=cause)
        with self._lock:
            self._dumps.append(path)
        return path

    # -- bundle assembly (runs with NO recorder lock held) ------------------

    def _build_body(
        self,
        cause: str,
        ctx: Dict[str, Any],
        now: float,
        events: List[Dict[str, Any]],
        series: Dict[str, Any],
        engines: Tuple[Any, ...],
        groups: Tuple[Any, ...],
    ) -> Dict[str, Any]:
        reg = metrics.registry()
        reg_dict = reg.as_dict()
        body: Dict[str, Any] = {
            "format": "raft_tpu.obs_bundle",
            "bundle_version": BUNDLE_VERSION,
            "t": now,
            "wall_time": time.time(),
            "window_s": self.window_s,
            "trigger": {"cause": cause, "ctx": ctx, "t": now},
            "events": events,
            "series": series,
            "metrics": reg_dict,
            "slow_traces": self._slow_traces(reg, reg_dict),
            "plans": {},
            "health": {"engines": [], "groups": []},
            "lockcheck": _lockcheck_state(),
            "fingerprint": _fingerprint(),
        }
        for e in engines:
            try:
                h = e.health()
            except Exception as err:
                h = {"error": f"{type(err).__name__}: {err}"}
            body["health"]["engines"].append(h)
            for index_id in (h.get("indexes") or {}):
                try:
                    body["plans"][index_id] = e.plan_explain(index_id)
                except Exception as err:
                    body["plans"][index_id] = f"error: {err}"
        for g in groups:
            try:
                h = g.health()
            except Exception as err:
                h = {"error": f"{type(err).__name__}: {err}"}
            body["health"]["groups"].append(h)
        return body

    def _slow_traces(
        self, reg: metrics.Registry, reg_dict: Dict[str, Any]
    ) -> List[Dict[str, Any]]:
        """The slowest exemplar-tagged requests with their complete span
        chains — the "which request made p99" evidence, resolved from
        histogram exemplars through the trace-id span index."""
        rows: List[Tuple[float, str, str]] = []
        for key, h in reg_dict.get("histograms", {}).items():
            for ex in h.get("exemplars", ()):
                if ex.get("trace_id"):
                    rows.append((float(ex["value"]), str(ex["trace_id"]), key))
        rows.sort(reverse=True)
        out: List[Dict[str, Any]] = []
        seen: set = set()
        for value, trace_id, metric_key in rows:
            if trace_id in seen:
                continue
            seen.add(trace_id)
            spans = list(request.iter_trace_spans(reg, trace_id))
            out.append({
                "trace_id": trace_id,
                "value": value,
                "metric": metric_key,
                "spans": spans,
            })
            if len(out) >= self.slow_traces:
                break
        return out


def _lockcheck_state() -> Dict[str, Any]:
    exercised, declared = lockcheck.coverage()
    return {
        "enabled": lockcheck.is_enabled(),
        "edges": [
            [a, b, n] for (a, b), n in sorted(lockcheck.edges().items())
        ],
        "violations": list(lockcheck.violations()),
        "coverage": {
            "exercised": sorted(list(e) for e in exercised),
            "declared": sorted(list(e) for e in declared),
        },
        "field_coverage": lockcheck.field_coverage(),
        "field_violations": list(lockcheck.field_violations()),
    }


def _fingerprint() -> Dict[str, Any]:
    env = {
        k: v for k, v in sorted(os.environ.items()) if k.startswith("RAFT_TPU_")
    }
    out: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "pid": os.getpid(),
        "argv0": sys.argv[0] if sys.argv else "",
        "env": env,
    }
    try:
        import jax

        out["jax"] = jax.__version__
        out["jax_backend"] = jax.default_backend()
    except Exception:
        out["jax"] = None
    return out


# -- bundle reading ----------------------------------------------------------


def load_bundle(path: str) -> Dict[str, Any]:
    """Load + CRC-verify one bundle file (raises
    :class:`~raft_tpu.core.errors.CorruptIndexError` on a damaged
    envelope — which :func:`raft_tpu.core.serialize.atomic_write`
    guarantees can only happen to a file produced by something other
    than a completed :meth:`FlightRecorder.dump`)."""
    with open(path, "rb") as f:
        _, payload = serialize.load_stream(f, BUNDLE_KIND)
        return json.loads(payload.read().decode("utf-8"))


def list_bundles(out_dir: str) -> List[str]:
    """Bundle files under ``out_dir``, oldest first."""
    try:
        names = sorted(
            n for n in os.listdir(out_dir) if n.endswith(BUNDLE_SUFFIX)
        )
    except FileNotFoundError:
        return []
    return [os.path.join(out_dir, n) for n in names]


# -- the process-wide recorder (what the serving hooks talk to) --------------

_active: Optional[FlightRecorder] = None


def install(out_dir: str, **kwargs) -> FlightRecorder:
    """Construct a :class:`FlightRecorder` and make it the process-wide
    active one (what every ``note_*`` hook and ``ServingEngine``'s
    maintenance tick feed). Returns it for attach/dump calls."""
    global _active
    _active = FlightRecorder(out_dir, **kwargs)
    return _active


def installed() -> Optional[FlightRecorder]:
    return _active


def uninstall() -> Optional[FlightRecorder]:
    """Deactivate (and return) the active recorder."""
    global _active
    r = _active
    _active = None
    return r


def tick(reg: Optional[metrics.Registry] = None) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.tick(reg)


def dump(cause: str = "manual", **ctx) -> Optional[str]:
    r = _active
    if r is None:
        return None
    return r.dump(cause=cause, ctx=ctx)


def note_fault(point: str, kind: str) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_fault(point, kind)


def note_slo_transition(
    index_id: str,
    transition: str,
    burn_fast: Optional[float] = None,
    burn_slow: Optional[float] = None,
) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_slo_transition(index_id, transition, burn_fast, burn_slow)


def note_breaker(target: str, to: str) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_breaker(target, to)


def note_plan_flip(index_id: str, epoch: int) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_plan_flip(index_id, epoch)


def note_worker_death(index: str) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_worker_death(index)


def note_election(index_id: str, epoch: int, leader: str, reason: str) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_election(index_id, epoch, leader, reason)


def note_fenced(follower: str, epoch: int, fence_epoch: int) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_fenced(follower, epoch, fence_epoch)


def note_scale(group: str, direction: str, n_replicas: int) -> None:
    r = _active
    if r is not None and metrics.is_enabled():
        r.note_scale(group, direction, n_replicas)
