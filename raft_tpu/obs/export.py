"""Export the obs registry as Chrome-trace/Perfetto ``trace_events``
JSON and as a metrics JSONL snapshot.

The trace format is the Trace Event Format's JSON Object Format: a
top-level ``{"traceEvents": [...]}`` where each span is a complete
duration event (``"ph": "X"`` with ``ts``/``dur`` in microseconds) and
each counter is sampled once at trace end as a counter event
(``"ph": "C"``). Files written by :func:`write_trace` open directly in
``ui.perfetto.dev`` (or ``chrome://tracing``); :func:`validate_trace`
is the schema check the round-trip tests and ``tools/obs_report.py``
share.

Spans tagged with request trace IDs (:mod:`raft_tpu.obs.request`)
additionally produce **flow events** (``"ph": "s"/"t"/"f"``): one arrow
chain per trace ID, binding to the tagged slices in timestamp order.
That is what makes one request render as a connected track across
threads in Perfetto — the synthetic per-request ``serve.queue`` slice,
the worker thread's ``serve.dispatch``, and the tiered ``host.fetch`` /
refine slices are visually chained even though they live on different
``tid`` s.
"""
from __future__ import annotations

import io
import json
import os
import zlib
from typing import Any, Dict, List, Optional

from raft_tpu.core import serialize
from raft_tpu.obs import metrics as _metrics


def chrome_trace(registry: Optional[_metrics.Registry] = None) -> Dict[str, Any]:
    """Build the ``trace_events`` document from a registry snapshot."""
    reg = registry or _metrics.registry()
    pid = os.getpid()
    events = []
    end_ts = 0.0
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in reg.spans():
        end_ts = max(end_ts, s["ts_us"] + s["dur_us"])
        args = {**s["args"], "depth": s["depth"]}
        trace = s.get("trace") or ()
        if trace:
            args["trace"] = list(trace)
        ev = {
            "ph": "X",
            "name": s["name"],
            "cat": "raft_tpu",
            "ts": round(s["ts_us"], 3),
            "dur": round(s["dur_us"], 3),
            "pid": pid,
            "tid": s["tid"],
            "args": args,
        }
        events.append(ev)
        for t in trace:
            by_trace.setdefault(t, []).append(ev)
    # one flow chain per trace ID: start on the earliest tagged slice,
    # step through the rest, finish (enclosing bind) on the last — this
    # is what draws the request's arrows across thread tracks
    for trace_id, evs in sorted(by_trace.items()):
        if len(evs) < 2:
            continue  # an arrow needs two endpoints
        evs.sort(key=lambda e: (e["ts"], e["args"]["depth"]))
        flow_id = zlib.crc32(trace_id.encode("utf-8"))
        for j, ev in enumerate(evs):
            ph = "s" if j == 0 else ("f" if j == len(evs) - 1 else "t")
            flow = {
                "ph": ph,
                "name": "request",
                "cat": "trace",
                "id": flow_id,
                "ts": ev["ts"],
                "pid": pid,
                "tid": ev["tid"],
                "args": {"trace": trace_id},
            }
            if ph == "f":
                flow["bp"] = "e"
            events.append(flow)
    snap = reg.as_dict()
    for key, value in snap["counters"].items():
        events.append(
            {
                "ph": "C",
                "name": key,
                "cat": "raft_tpu",
                "ts": round(end_ts, 3),
                "pid": pid,
                "tid": 0,
                "args": {"value": value},
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "raft_tpu.obs", "spans_dropped": snap["spans_dropped"]},
    }


def validate_trace(doc: Any) -> None:
    """Raise ``ValueError`` unless ``doc`` is a well-formed Trace Event
    Format JSON object (the contract ``ui.perfetto.dev`` parses)."""
    if not isinstance(doc, dict):
        raise ValueError("trace document must be a JSON object")
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("trace document must have a 'traceEvents' list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            raise ValueError(f"traceEvents[{i}] missing phase 'ph'")
        if ph == "X":
            if not isinstance(ev.get("name"), str):
                raise ValueError(f"traceEvents[{i}]: duration event needs a 'name'")
            for field in ("ts", "dur"):
                v = ev.get(field)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    raise ValueError(f"traceEvents[{i}]: '{field}' must be a number")
            if ev["dur"] < 0:
                raise ValueError(f"traceEvents[{i}]: negative 'dur'")
            for field in ("pid", "tid"):
                if not isinstance(ev.get(field), int):
                    raise ValueError(f"traceEvents[{i}]: '{field}' must be an int")
        elif ph == "C":
            if not isinstance(ev.get("name"), str):
                raise ValueError(f"traceEvents[{i}]: counter event needs a 'name'")
            if not isinstance(ev.get("args"), dict):
                raise ValueError(f"traceEvents[{i}]: counter event needs 'args'")
        elif ph in ("s", "t", "f"):
            if not isinstance(ev.get("name"), str):
                raise ValueError(f"traceEvents[{i}]: flow event needs a 'name'")
            if not isinstance(ev.get("id"), (int, str)) or isinstance(ev.get("id"), bool):
                raise ValueError(f"traceEvents[{i}]: flow event needs an 'id'")
            v = ev.get("ts")
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValueError(f"traceEvents[{i}]: 'ts' must be a number")
            for field in ("pid", "tid"):
                if not isinstance(ev.get(field), int):
                    raise ValueError(f"traceEvents[{i}]: '{field}' must be an int")


def write_trace(path: str, registry: Optional[_metrics.Registry] = None) -> str:
    """Write (and validate) the Chrome-trace JSON; returns ``path``."""
    doc = chrome_trace(registry)
    validate_trace(doc)
    payload = json.dumps(doc).encode("utf-8")
    # temp-fsync-rename: a crash mid-export must not tear a trace a
    # later tooling pass would choke on
    return serialize.atomic_write(path, lambda f: f.write(payload))


def load_trace(path: str) -> Dict[str, Any]:
    """Read + validate a trace file written by :func:`write_trace`."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    validate_trace(doc)
    return doc


def write_metrics_jsonl(path: str, registry: Optional[_metrics.Registry] = None) -> str:
    """Write the metrics + spans JSONL snapshot; returns ``path``."""
    reg = registry or _metrics.registry()
    buf = io.StringIO()
    reg.dump_jsonl(buf)
    payload = buf.getvalue().encode("utf-8")
    return serialize.atomic_write(path, lambda f: f.write(payload))
