"""Bounded ring-buffer time series over the metrics registry.

Everything in :mod:`raft_tpu.obs.metrics` is a point-in-time snapshot:
when an SLO alert fires or a breaker trips, the history that explains
*why* — the burn-rate trajectory, the queue-depth trend, the latency
drift — is already gone. This module retains it, bounded:

* :class:`TimeSeries` / :class:`HistogramSeries` — fixed-capacity rings
  of ``(t, value)`` / ``(t, bucket counts, sum, count)`` samples with
  windowed queries (``rate()``, ``mean()``, ``percentile()``,
  ``delta()``). Capacity bounds memory; the clock is injectable so the
  serving tests drive them with the same virtual clock as the batcher.
* :class:`SeriesBank` — auto-discovers registry instruments matching a
  name-prefix allowlist on every :meth:`SeriesBank.sample` tick (one
  consistent :meth:`~raft_tpu.obs.metrics.Registry.sample` snapshot per
  tick) and appends to the matching series.
* :class:`EwmaDetector` — EWMA-baseline drift detection over the bank.
  :func:`default_detectors` wires the four serving signals: latency
  drift, QPS cliff, coverage drop, burn-rate slope. Detected anomalies
  are returned as :class:`Anomaly` records; the flight recorder
  (:mod:`raft_tpu.obs.recorder`) turns them into ``obs.anomaly
  {signal,index_id}`` events.

Gate discipline mirrors the registry: :meth:`SeriesBank.sample` checks
:func:`raft_tpu.obs.metrics.is_enabled` first and allocates nothing on
the disabled path.

Thread-safety: NONE of these classes lock. The bank and its series are
owned by a single serializer — the :class:`~raft_tpu.obs.recorder.
FlightRecorder` mutates them only under its own ``obs.recorder`` lock
(an edge-free leaf: the registry snapshot is taken *before* the lock is
entered, so sampling never nests ``obs.recorder`` over
``obs.registry``). State lives in deques/dicts mutated in place, never
in rebound attributes, so ownership hand-off needs no per-sample
synchronization.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional, Sequence, Tuple

from raft_tpu.obs import metrics

#: registry name prefixes the bank retains by default — the serving
#: signals the drift detectors and ROADMAP items 1c/6 read
DEFAULT_TRACKED = (
    "serve.",
    "slo.",
    "robust.breaker.",
    "mutable.maintenance.",
    "replica.",
)

#: hard cap on distinct series a bank will materialize (memory backstop
#: against label-cardinality accidents; overflow is counted, not grown)
DEFAULT_MAX_SERIES = 256


class TimeSeries:
    """Fixed-capacity ring of ``(t, value)`` samples for one scalar
    instrument (counter or gauge). Appends evict the oldest sample —
    ``collections.deque(maxlen=...)`` ring semantics."""

    def __init__(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        capacity: int = 512,
        kind: str = "gauge",
    ):
        self.name = name
        self.labels = dict(labels or {})
        self.capacity = int(capacity)
        self.kind = kind
        self._samples: Deque[Tuple[float, float]] = deque(maxlen=self.capacity)

    def append(self, t: float, value: float) -> None:
        self._samples.append((float(t), float(value)))

    def __len__(self) -> int:
        return len(self._samples)

    def latest(self) -> Optional[Tuple[float, float]]:
        return self._samples[-1] if self._samples else None

    def points(self, since: Optional[float] = None) -> List[Tuple[float, float]]:
        if since is None:
            return list(self._samples)
        return [(t, v) for t, v in self._samples if t >= since]

    # -- windowed queries --------------------------------------------------

    def _window(self, window_s: float, now: float) -> List[Tuple[float, float]]:
        return self.points(since=now - window_s)

    def delta(self, window_s: float, now: float) -> float:
        """Last minus first sample value inside the window (0.0 with
        fewer than two samples)."""
        pts = self._window(window_s, now)
        if len(pts) < 2:
            return 0.0
        return pts[-1][1] - pts[0][1]

    def rate(self, window_s: float, now: float) -> float:
        """``delta`` per second over the actual sampled span — for a
        counter this is the event rate, for a gauge the slope."""
        pts = self._window(window_s, now)
        if len(pts) < 2:
            return 0.0
        span = pts[-1][0] - pts[0][0]
        if span <= 0.0:
            return 0.0
        return (pts[-1][1] - pts[0][1]) / span

    def mean(self, window_s: float, now: float) -> float:
        pts = self._window(window_s, now)
        if not pts:
            return 0.0
        return sum(v for _, v in pts) / len(pts)

    def percentile(self, q: float, window_s: float, now: float) -> float:
        """Linear-interpolated percentile (``q`` in [0, 100]) over the
        sample *values* in the window."""
        vals = sorted(v for _, v in self._window(window_s, now))
        if not vals:
            return 0.0
        if len(vals) == 1:
            return vals[0]
        pos = (q / 100.0) * (len(vals) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "labels": dict(self.labels),
            "kind": self.kind,
            "points": [[t, v] for t, v in self._samples],
        }


class HistogramSeries:
    """Fixed-capacity ring of histogram snapshots ``(t, bucket counts,
    sum, count)``. Windowed queries difference the first and last
    snapshot inside the window, so they describe exactly the
    observations that landed between those two sampler ticks."""

    def __init__(
        self,
        name: str,
        buckets: Sequence[float],
        labels: Optional[Dict[str, str]] = None,
        capacity: int = 512,
    ):
        self.name = name
        self.labels = dict(labels or {})
        self.buckets = tuple(float(b) for b in buckets)
        self.capacity = int(capacity)
        self.kind = "histogram"
        #: (t, counts incl. the +Inf bucket, sum, count)
        self._samples: Deque[Tuple[float, Tuple[int, ...], float, int]] = deque(
            maxlen=self.capacity
        )

    def append(
        self, t: float, counts: Sequence[int], total: float, count: int
    ) -> None:
        self._samples.append((float(t), tuple(counts), float(total), int(count)))

    def __len__(self) -> int:
        return len(self._samples)

    def latest(self) -> Optional[Tuple[float, Tuple[int, ...], float, int]]:
        return self._samples[-1] if self._samples else None

    def points(self, since: Optional[float] = None) -> List[Tuple[float, float]]:
        """The cumulative observation count per sample — the scalar
        shadow of the ring (what the bundle plots as the timeline)."""
        if since is None:
            return [(t, float(c)) for t, _, _, c in self._samples]
        return [(t, float(c)) for t, _, _, c in self._samples if t >= since]

    def _ends(
        self, window_s: float, now: float
    ) -> Optional[Tuple[Tuple[float, Tuple[int, ...], float, int], ...]]:
        horizon = now - window_s
        inside = [s for s in self._samples if s[0] >= horizon]
        if len(inside) < 2:
            return None
        return inside[0], inside[-1]

    def delta(self, window_s: float, now: float) -> float:
        """Observation count that landed inside the window."""
        ends = self._ends(window_s, now)
        if ends is None:
            return 0.0
        return float(ends[1][3] - ends[0][3])

    def rate(self, window_s: float, now: float) -> float:
        ends = self._ends(window_s, now)
        if ends is None:
            return 0.0
        span = ends[1][0] - ends[0][0]
        if span <= 0.0:
            return 0.0
        return (ends[1][3] - ends[0][3]) / span

    def mean(self, window_s: float, now: float) -> float:
        ends = self._ends(window_s, now)
        if ends is None:
            return 0.0
        dcount = ends[1][3] - ends[0][3]
        if dcount <= 0:
            return 0.0
        return (ends[1][2] - ends[0][2]) / dcount

    def percentile(self, q: float, window_s: float, now: float) -> float:
        """Bucket-interpolated percentile over the observations inside
        the window (the Prometheus ``histogram_quantile`` estimate).
        Values landing in the +Inf bucket resolve to the largest finite
        bound — a conservative floor for the true tail."""
        ends = self._ends(window_s, now)
        if ends is None:
            return 0.0
        dcounts = [b - a for a, b in zip(ends[0][1], ends[1][1])]
        total = sum(dcounts)
        if total <= 0:
            return 0.0
        target = (q / 100.0) * total
        cum = 0.0
        for i, dc in enumerate(dcounts):
            if dc <= 0:
                continue
            if cum + dc >= target:
                if i >= len(self.buckets):  # +Inf bucket
                    return self.buckets[-1] if self.buckets else 0.0
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (target - cum) / dc
                return lo + (hi - lo) * frac
            cum += dc
        return self.buckets[-1] if self.buckets else 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "labels": dict(self.labels),
            "kind": self.kind,
            "buckets": list(self.buckets),
            "points": [
                [t, list(c), s, n] for t, c, s, n in self._samples
            ],
        }


class SeriesBank:
    """A bounded collection of time series auto-discovered from a
    :class:`~raft_tpu.obs.metrics.Registry`.

    :meth:`sample` takes one consistent registry snapshot (via
    :meth:`Registry.sample`) and appends every instrument whose name
    starts with a tracked prefix to its series, creating series lazily
    up to ``max_series``. Overflow beyond the cap is counted in
    ``stats()["dropped"]`` rather than grown — a label-cardinality
    accident must not turn the retention layer into the leak it exists
    to observe.
    """

    def __init__(
        self,
        tracked: Sequence[str] = DEFAULT_TRACKED,
        capacity: int = 512,
        max_series: int = DEFAULT_MAX_SERIES,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.tracked = tuple(tracked)
        self.capacity = int(capacity)
        self.max_series = int(max_series)
        self.clock = clock
        self._series: Dict[str, Any] = {}
        self._stats: Dict[str, int] = {"samples": 0, "dropped": 0}

    def __len__(self) -> int:
        return len(self._series)

    def stats(self) -> Dict[str, int]:
        return dict(self._stats)

    def sample(
        self,
        reg: Optional[metrics.Registry] = None,
        now: Optional[float] = None,
    ) -> None:
        """One sampler tick: snapshot matching registry instruments and
        append. Zero-allocation no-op when ``RAFT_TPU_OBS`` is off."""
        if not metrics.is_enabled():
            return
        if reg is None:
            reg = metrics.registry()
        rows = reg.sample(self.tracked)
        self.ingest(rows, self.clock() if now is None else now)

    def ingest(
        self, rows: Sequence[Tuple[str, str, Any, Any]], now: float
    ) -> None:
        """Append one pre-taken :meth:`Registry.sample` snapshot. Split
        from :meth:`sample` so an owner holding its own lock can take
        the registry snapshot *outside* that lock (the flight recorder's
        edge-free discipline) and ingest under it."""
        self._stats["samples"] += 1
        for kind, name, labels, payload in rows:
            key = metrics.Registry._fmt_key(name, labels)
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self._stats["dropped"] += 1
                    continue
                ldict = dict(labels)
                if kind == "histogram":
                    s = HistogramSeries(
                        name, payload[0], labels=ldict, capacity=self.capacity
                    )
                else:
                    s = TimeSeries(
                        name, labels=ldict, capacity=self.capacity, kind=kind
                    )
                self._series[key] = s
            if kind == "histogram":
                _, counts, total, count = payload
                s.append(now, counts, total, count)
            else:
                s.append(now, payload)

    def find(self, name: str) -> List[Any]:
        """Every series for metric ``name``, any label set."""
        return [s for s in self._series.values() if s.name == name]

    def get(self, name: str, **labels) -> Optional[Any]:
        key = metrics.Registry._fmt_key(name, metrics._labels_key(labels))
        return self._series.get(key)

    def series(self) -> Iterator[Any]:
        return iter(self._series.values())

    def as_dict(self) -> Dict[str, Any]:
        return {
            "stats": self.stats(),
            "series": [s.as_dict() for s in self._series.values()],
        }


# -- drift detection ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One drift-detector firing."""

    signal: str      # "latency_drift" | "qps_cliff" | ...
    index_id: str    # per-index signals; "all" for unlabeled ones
    value: float     # the observed windowed value
    baseline: float  # the EWMA baseline it was compared against
    t: float

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class EwmaDetector:
    """EWMA-baseline drift detector over one extracted signal.

    ``extract(bank, now, window_s)`` yields ``(index_id, value)`` pairs;
    each key keeps its own EWMA baseline. After ``warmup`` observations
    a value is anomalous when

    * ``mode="ratio_above"``: ``value > threshold * baseline``
      (and ``baseline > min_baseline`` — tiny baselines never alarm),
    * ``mode="ratio_below"``: ``value < threshold * baseline``
      (same baseline floor — a QPS cliff from ~zero is not a cliff),
    * ``mode="abs_above"``: ``value > threshold`` (baseline reported
      for context only).

    The baseline always folds the new value in, anomalous or not — a
    sustained regime change stops alarming once the baseline catches
    up, which is what keeps a recorder from dumping forever.
    """

    def __init__(
        self,
        signal: str,
        extract: Callable[["SeriesBank", float, float], Sequence[Tuple[str, float]]],
        mode: str = "ratio_above",
        threshold: float = 3.0,
        alpha: float = 0.3,
        warmup: int = 5,
        min_baseline: float = 0.0,
        window_s: float = 30.0,
    ):
        if mode not in ("ratio_above", "ratio_below", "abs_above"):
            raise ValueError(f"unknown detector mode {mode!r}")
        self.signal = signal
        self.mode = mode
        self.threshold = float(threshold)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.min_baseline = float(min_baseline)
        self.window_s = float(window_s)
        self._extract = extract
        #: key -> [ewma, n_observations] (mutated in place)
        self._state: Dict[str, List[float]] = {}

    def check(self, bank: "SeriesBank", now: float) -> List[Anomaly]:
        out: List[Anomaly] = []
        for key, value in self._extract(bank, now, self.window_s):
            value = float(value)
            st = self._state.get(key)
            if st is None:
                self._state[key] = [value, 1.0]
                continue
            ewma, n = st
            if n >= self.warmup and self._anomalous(value, ewma):
                out.append(
                    Anomaly(
                        signal=self.signal, index_id=key,
                        value=value, baseline=ewma, t=now,
                    )
                )
            st[0] = self.alpha * value + (1.0 - self.alpha) * ewma
            st[1] = n + 1.0
        return out

    def _anomalous(self, value: float, baseline: float) -> bool:
        if self.mode == "abs_above":
            return value > self.threshold
        if baseline <= self.min_baseline:
            return False
        if self.mode == "ratio_above":
            return value > self.threshold * baseline
        return value < self.threshold * baseline


# -- the four serving signals ------------------------------------------------


def _latency_p99(bank: SeriesBank, now: float, w: float) -> List[Tuple[str, float]]:
    out = []
    for s in bank.find("serve.time_in_queue_ms"):
        if s.kind != "histogram" or s.delta(w, now) <= 0:
            continue
        out.append((s.labels.get("index_id", "all"), s.percentile(99.0, w, now)))
    return out


def _qps(bank: SeriesBank, now: float, w: float) -> List[Tuple[str, float]]:
    per_index: Dict[str, float] = {}
    for s in bank.find("serve.requests"):
        key = s.labels.get("index_id", "all")
        per_index[key] = per_index.get(key, 0.0) + s.rate(w, now)
    return sorted(per_index.items())


def _coverage(bank: SeriesBank, now: float, w: float) -> List[Tuple[str, float]]:
    out = []
    for s in bank.find("serve.coverage"):
        latest = s.latest()
        if latest is None or latest[0] < now - w:
            continue
        out.append((s.labels.get("index_id", "all"), latest[1]))
    return out


def _burn_slope(bank: SeriesBank, now: float, w: float) -> List[Tuple[str, float]]:
    out = []
    for s in bank.find("slo.burn_rate"):
        if s.labels.get("window") != "fast":
            continue
        out.append((s.labels.get("index_id", "all"), s.rate(w, now)))
    return out


def default_detectors() -> List[EwmaDetector]:
    """The stock serving-signal detector set:

    * ``latency_drift`` — windowed p99 of ``serve.time_in_queue_ms``
      above 3x its EWMA baseline;
    * ``qps_cliff`` — per-index ``serve.requests`` rate below 30% of
      baseline (baselines under 1 req/s never alarm);
    * ``coverage_drop`` — latest ``serve.coverage`` below 90% of
      baseline (degraded sharded responses);
    * ``burn_rate_slope`` — fast-window ``slo.burn_rate`` climbing
      faster than 0.5/s (budget exhaustion on the way, ahead of the
      alert itself).
    """
    return [
        EwmaDetector(
            "latency_drift", _latency_p99,
            mode="ratio_above", threshold=3.0, min_baseline=0.05,
        ),
        EwmaDetector(
            "qps_cliff", _qps,
            mode="ratio_below", threshold=0.3, min_baseline=1.0,
        ),
        EwmaDetector(
            "coverage_drop", _coverage,
            mode="ratio_below", threshold=0.9, min_baseline=0.1, warmup=3,
        ),
        EwmaDetector(
            "burn_rate_slope", _burn_slope,
            mode="abs_above", threshold=0.5, warmup=2,
        ),
    ]
