"""Dev sweep: fused IVF-PQ scan configs on the 1M x 128 bench shape.

Run EXCLUSIVELY on the TPU. Usage: python tools/sweep_pq.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax_comp"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from raft_tpu.neighbors import brute_force, ivf_pq  # noqa: E402
from raft_tpu.neighbors.refine import refine  # noqa: E402
from raft_tpu.ops.distance import DistanceType  # noqa: E402
from raft_tpu.stats import neighborhood_recall  # noqa: E402

N, D, NQ, K = 1_000_000, 128, 1024, 10


def timed(fn, nrep=3, inner=4):
    out = fn()
    float(jnp.sum(out[0]))
    best = float("inf")
    for _ in range(nrep):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn()
        float(jnp.sum(out[0]))
        best = min(best, (time.perf_counter() - t0) / inner)
    return best, out


def main():
    key = jax.random.PRNGKey(1234)
    kc, ka, kb, kq1, kq2 = jax.random.split(key, 5)
    centers = jax.random.normal(kc, (1000, D), jnp.float32)
    dataset = centers[jax.random.randint(ka, (N,), 0, 1000)] + jax.random.normal(
        kb, (N, D), jnp.float32
    )
    queries = centers[jax.random.randint(kq1, (NQ,), 0, 1000)] + jax.random.normal(
        kq2, (NQ, D), jnp.float32
    )
    float(jnp.sum(dataset[0]))

    bf = brute_force.build(dataset, metric=DistanceType.L2Expanded)
    _, ei = brute_force.search(bf, queries, K, query_batch=NQ, dataset_tile=262144)
    gt = np.asarray(ei)
    print("# gt done", flush=True)

    variants = {
        "p4_d32": dict(pq_dim=32, pq_bits=4),
        "nib_d32": dict(pq_dim=32, pq_bits=8, pq_kind="nibble"),
        "p4_d64": dict(pq_dim=64, pq_bits=4),
    }
    idxs = {}
    for name, kw in variants.items():
        t0 = time.perf_counter()
        idxs[name] = ivf_pq.build(
            dataset,
            ivf_pq.IvfPqIndexParams(
                n_lists=1024, kmeans_n_iters=10, kmeans_trainset_fraction=0.1,
                list_cap_factor=1.1, **kw,
            ),
        )
        float(jnp.sum(idxs[name].list_sizes))
        code_mb = idxs[name].codes.size / 1e6
        print(f"# build {name}: {time.perf_counter()-t0:.1f}s  codes={code_mb:.0f}MB "
              f"max_list={idxs[name].max_list}", flush=True)

    from _artifact import Recorder

    art = Recorder("sweep_pq", {"n": N, "dim": D, "nq": NQ, "k": K})
    print(f"# {'config':52s} {'qps':>10s} {'recall':>8s}")
    for name, npr, pf, g, rr in [
        ("p4_d32", 30, 32, 8, 4),
        ("p4_d32", 30, 32, 8, 8),
        ("p4_d32", 30, 32, 16, 8),
        ("nib_d32", 30, 32, 8, 4),
        ("nib_d32", 30, 32, 8, 8),
        ("nib_d32", 20, 32, 8, 4),
        ("nib_d32", 30, 32, 16, 4),
        ("p4_d64", 30, 32, 8, 4),
        ("p4_d64", 30, 32, 16, 4),
    ]:
        idx = idxs[name]
        sp = ivf_pq.IvfPqSearchParams(
            n_probes=npr, fused_qt=128, fused_probe_factor=pf, fused_group=g
        )

        def run(sp=sp, idx=idx, rr=rr):
            _, cand = ivf_pq.search(idx, queries, rr * K, sp, mode="fused")
            return refine(dataset, queries, cand, K, metric=DistanceType.L2Expanded)

        tag = f"{name} npr={npr} pf={pf} G={g} refine={rr}x"
        try:
            dt, (v, i) = timed(run)
        except Exception as e:  # noqa: BLE001
            print(f"# {tag:52s} FAILED {type(e).__name__}: {str(e)[:100]}", flush=True)
            continue
        rec = float(neighborhood_recall(np.asarray(i)[:, :K], gt))  # graft-lint: ignore[sync-transfer-in-loop] — post-timed recall readout
        print(f"# {tag:52s} {NQ/dt:>10,.0f} {rec:>8.4f}", flush=True)
        art.add({"config": tag, "qps": round(NQ / dt, 1), "recall": round(rec, 4)})

    art.set_context(device=str(jax.devices()[0]))


if __name__ == "__main__":
    main()
