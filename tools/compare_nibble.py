"""Recall parity study: additive nibble codebooks vs kmeans-256 vs 4-bit
at equal pq_dim and n_probes (VERDICT r4 weak #4 — the nibble book's
recall-vs-default parity was unproven beyond smoke scale).

Runs anywhere (CPU ok — recall doesn't need the chip; only wall-times
do). Writes an incremental artifact under ``artifacts/tpu/``.

    python tools/compare_nibble.py [n_rows]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

if os.environ.get("RAFT_TPU_FORCE_CPU"):
    # the axon plugin ignores JAX_PLATFORMS once loaded; this works
    # because it runs before the first backend use
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from raft_tpu.neighbors import brute_force, ivf_pq
from raft_tpu.neighbors.refine import refine
from raft_tpu.ops.distance import DistanceType
from raft_tpu.stats import neighborhood_recall

D, NQ, K = 64, 256, 10
N_CENTERS = 500


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 300_000
    key = jax.random.PRNGKey(7)
    kc, ka, kb, kq1, kq2 = jax.random.split(key, 5)
    centers = jax.random.normal(kc, (N_CENTERS, D), jnp.float32)
    dataset = centers[jax.random.randint(ka, (n,), 0, N_CENTERS)] + jax.random.normal(
        kb, (n, D), jnp.float32
    )
    queries = centers[jax.random.randint(kq1, (NQ,), 0, N_CENTERS)] + jax.random.normal(
        kq2, (NQ, D), jnp.float32
    )
    bf = brute_force.build(dataset, metric=DistanceType.L2Expanded)
    _, ei = brute_force.search(bf, queries, K)
    gt = np.asarray(ei)
    print("# gt done", flush=True)

    from _artifact import Recorder

    art = Recorder(
        "nibble_vs_kmeans256",
        {"n": n, "dim": D, "nq": NQ, "k": K,
         "device": str(jax.devices()[0]),
         "note": "recall parity at equal pq_dim/n_probes; scan path (no kernel noise)"},
    )

    n_lists = max(64, int(n ** 0.5 / 2) // 64 * 64)
    variants = {
        "kmeans256": dict(pq_dim=16, pq_bits=8),
        "nibble": dict(pq_dim=16, pq_bits=8, pq_kind="nibble"),
        "pq4": dict(pq_dim=16, pq_bits=4),
    }
    idxs = {}
    for name, kw in variants.items():
        idxs[name] = ivf_pq.build(
            dataset,
            ivf_pq.IvfPqIndexParams(
                n_lists=n_lists, kmeans_n_iters=10, kmeans_trainset_fraction=0.2,
                list_cap_factor=1.1, **kw,
            ),
        )
        print(f"# built {name}", flush=True)

    for npr in (10, 20, 40):
        for name, idx in idxs.items():
            sp = ivf_pq.IvfPqSearchParams(n_probes=npr)
            _, i = ivf_pq.search(idx, queries, K, sp, mode="scan")
            rec = float(neighborhood_recall(np.asarray(i), gt))  # graft-lint: ignore[sync-transfer-in-loop] — recall measurement; throughput not at stake
            _, cand = ivf_pq.search(idx, queries, 4 * K, sp, mode="scan")
            _, ri = refine(dataset, queries, cand, K, metric=DistanceType.L2Expanded)
            rrec = float(neighborhood_recall(np.asarray(ri), gt))  # graft-lint: ignore[sync-transfer-in-loop] — recall measurement; throughput not at stake
            row = {"variant": name, "n_probes": npr,
                   "recall": round(rec, 4), "recall_refine4x": round(rrec, 4),
                   "code_bytes_per_row": int(idxs[name].codes.shape[-1])}
            art.add(row)
            print(f"# {name:10s} npr={npr:3d} recall={rec:.4f} refine4x={rrec:.4f}", flush=True)


if __name__ == "__main__":
    main()
