"""Generate the notebooks/ set — the analog of the reference's
``notebooks/`` (VectorSearch_QuestionRetrieval / ivf_flat_example /
tutorial_ivf_pq). Cells are authored here as plain strings so the .ipynb
JSON stays valid and reviewable; ``tests/test_notebooks.py`` executes
every code cell (no jupyter needed). Re-run after editing:

    python tools/make_notebooks.py
"""
import json
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def md(text):
    return {"cell_type": "markdown", "metadata": {}, "source": text.splitlines(keepends=True)}


def code(text):
    return {
        "cell_type": "code",
        "execution_count": None,
        "metadata": {},
        "outputs": [],
        "source": text.strip("\n").splitlines(keepends=True),
    }


def notebook(cells):
    return {
        "cells": cells,
        "metadata": {
            "kernelspec": {"display_name": "Python 3", "language": "python", "name": "python3"},
            "language_info": {"name": "python", "version": "3.12"},
        },
        "nbformat": 4,
        "nbformat_minor": 5,
    }


SETUP = """
import os
import numpy as np
import jax
import jax.numpy as jnp

# CI smoke switch: shrink sizes so every notebook executes in seconds
SMOKE = bool(os.environ.get("RAFT_TPU_TUTORIAL_SMOKE"))
"""

VECTOR_SEARCH = notebook([
    md("""# Vector search end to end: question retrieval shaped workload

The TPU edition of the reference's `VectorSearch_QuestionRetrieval.ipynb`:
embed a corpus (synthetic stand-in for sentence embeddings in this
zero-egress environment — swap in your own `[n, d]` float32 matrix), build
ANN indexes, and compare recall/throughput against exact search."""),
    code(SETUP + """
from raft_tpu.bench.datasets import make_clustered

n = 20_000 if SMOKE else 200_000
dim = 96  # typical sentence-embedding width after PCA
ds = make_clustered("corpus", n=n, dim=dim, n_queries=512, seed=0)
corpus, queries = jnp.asarray(ds.base), jnp.asarray(ds.queries)
print(corpus.shape, queries.shape)
"""),
    md("""## Exact baseline

Brute force is one MXU pairwise-distance pass + top-k — on TPU this is
fast enough to serve as more than a baseline at moderate corpus sizes."""),
    code("""
import time
from raft_tpu.neighbors import brute_force
from raft_tpu.ops.distance import DistanceType

k = 10
bf = brute_force.build(corpus, metric=DistanceType.L2Expanded)
t0 = time.perf_counter()
_, gt = brute_force.search(bf, queries, k)
gt = np.asarray(gt)
print(f"exact: {queries.shape[0] / (time.perf_counter() - t0):,.0f} QPS")
"""),
    md("""## ANN: CAGRA graph search

The graph index answers the same queries at a fraction of the compute;
`itopk_size` moves along the recall/QPS curve."""),
    code("""
from raft_tpu.neighbors import cagra
from raft_tpu.stats import neighborhood_recall

gidx = cagra.build(corpus, cagra.CagraIndexParams(
    intermediate_graph_degree=32, graph_degree=16,
    nn_descent_niter=8 if SMOKE else 20,
))
for itopk in (32, 64):
    t0 = time.perf_counter()
    _, ids = cagra.search(gidx, queries, k, cagra.CagraSearchParams(itopk_size=itopk))
    qps = queries.shape[0] / (time.perf_counter() - t0)
    rec = float(neighborhood_recall(np.asarray(ids), gt))
    print(f"cagra itopk={itopk:3d}: recall@{k}={rec:.3f}  {qps:,.0f} QPS")
"""),
    md("""## Single-question latency

For interactive retrieval, `plan_search_params` picks the low-latency
schedule (wide beam, fewer sequential hops) when the batch is tiny."""),
    code("""
sp = cagra.plan_search_params(1, k, corpus.shape[0])
q1 = queries[:1]
cagra.search(gidx, q1, k, sp)  # warm the compile
t0 = time.perf_counter()
_, one = cagra.search(gidx, q1, k, sp)
np.asarray(one)
print(f"single-question latency: {(time.perf_counter() - t0) * 1e3:.1f} ms "
      f"(plan: width={sp.search_width})")
"""),
    md("""Where to go next: `tutorial_ivf_pq.ipynb` for memory-bound corpora,
`docs/vector_search_tutorial.md` for the full API walkthrough
(filtering, serialization, multi-device sharding)."""),
])

IVF_FLAT = notebook([
    md("""# IVF-Flat on TPU

The analog of the reference's `ivf_flat_example.ipynb`: cluster the
dataset into inverted lists, probe only the closest lists at query time.
On TPU the probed lists are scanned by a fused Pallas kernel that DMAs
only the probed rows."""),
    code(SETUP + """
from raft_tpu.bench.datasets import make_clustered
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.ops.distance import DistanceType
from raft_tpu.stats import neighborhood_recall

n = 20_000 if SMOKE else 500_000
ds = make_clustered("ivf_demo", n=n, dim=64, n_queries=256, seed=1)
X, Q = jnp.asarray(ds.base), jnp.asarray(ds.queries)
k = 10
_, gt = brute_force.search(brute_force.build(X), Q, k)
gt = np.asarray(gt)
"""),
    md("""## Build

`n_lists` ~ sqrt(n) is the usual starting point; `list_cap_factor`
bounds list imbalance so the dense scan stays rectangular."""),
    code("""
n_lists = 64 if SMOKE else 1024
index = ivf_flat.build(X, ivf_flat.IvfFlatIndexParams(
    n_lists=n_lists, kmeans_n_iters=10, list_cap_factor=1.2,
))
sizes = np.asarray(index.list_sizes)
print(f"{n_lists} lists, sizes min/mean/max = {sizes.min()}/{sizes.mean():.0f}/{sizes.max()}")
"""),
    md("""## The recall / n_probes curve"""),
    code("""
for n_probes in (1, 4, 16, n_lists // 2):
    _, ids = ivf_flat.search(index, Q, k, n_probes=n_probes)
    rec = float(neighborhood_recall(np.asarray(ids), gt))
    print(f"n_probes={n_probes:4d}  recall@{k} = {rec:.4f}")
"""),
    md("""## Extending and filtering

Indexes grow in place (`extend`), and a `Bitset` prefilter excludes rows
at scan time — the reference's deleted-rows workflow."""),
    code("""
from raft_tpu.core.bitset import Bitset

index2 = ivf_flat.extend(index, X[:100])  # re-add some rows
print("extended size:", index2.size)
banned = Bitset.from_unset_indices(index.size, np.arange(0, index.size, 2))
_, ids = ivf_flat.search(index, Q, k, n_probes=16, prefilter=banned)
ids = np.asarray(ids)
print("only odd ids returned:", bool(((ids % 2 == 1) | (ids < 0)).all()))
"""),
])

IVF_PQ = notebook([
    md("""# IVF-PQ: searching a compressed index

The analog of the reference's `tutorial_ivf_pq.ipynb`. Product
quantization stores each vector as `pq_dim` small codes — 8-64x smaller
than raw float32 — and scans lists in the compressed domain (ADC). On
TPU the scan is a multi-hot LUT matmul on the MXU."""),
    code(SETUP + """
from raft_tpu.bench.datasets import make_clustered
from raft_tpu.neighbors import brute_force, ivf_pq
from raft_tpu.neighbors.refine import refine
from raft_tpu.ops.distance import DistanceType
from raft_tpu.stats import neighborhood_recall

n = 20_000 if SMOKE else 500_000
ds = make_clustered("pq_demo", n=n, dim=64, n_queries=256, seed=2)
X, Q = jnp.asarray(ds.base), jnp.asarray(ds.queries)
k = 10
_, gt = brute_force.search(brute_force.build(X), Q, k)
gt = np.asarray(gt)
"""),
    md("""## Compression trade-offs

`pq_dim` sets codes per vector, `pq_bits` their width. Sub-byte widths
bit-pack (two 4-bit codes per byte; 5/6-bit spanning layouts), and
`pq_kind="nibble"` gives 256 effective centers per subspace at 4-bit
decode cost — the TPU answer to the reference's fp8 LUTs."""),
    code("""
n_lists = 32 if SMOKE else 1024
raw_mb = X.size * 4 / 1e6
for tag, kw in {
    "pq8x16 (default)": dict(pq_dim=16, pq_bits=8),
    "pq4x16 (packed)": dict(pq_dim=16, pq_bits=4),
    "nibble x16": dict(pq_dim=16, pq_bits=8, pq_kind="nibble"),
}.items():
    idx = ivf_pq.build(X, ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, kmeans_n_iters=10, **kw))
    _, ids = ivf_pq.search(idx, Q, k, ivf_pq.IvfPqSearchParams(n_probes=n_lists // 4))
    rec = float(neighborhood_recall(np.asarray(ids), gt))
    print(f"{tag:18s} codes {idx.codes.size / 1e6:6.1f} MB ({raw_mb / (idx.codes.size / 1e6):4.0f}x) "
          f"recall@{k} = {rec:.3f}")
"""),
    md("""## Refinement: compressed candidates, exact ranks

Over-fetch `r*k` candidates from the compressed index and re-rank them
against the raw vectors — most of the recall of exact search at a
fraction of its cost."""),
    code("""
idx = ivf_pq.build(X, ivf_pq.IvfPqIndexParams(n_lists=n_lists, pq_dim=16, kmeans_n_iters=10))
sp = ivf_pq.IvfPqSearchParams(n_probes=n_lists // 4)
for r in (1, 2, 4):
    _, cand = ivf_pq.search(idx, Q, r * k, sp)
    if r > 1:
        _, cand = refine(X, Q, cand, k, metric=DistanceType.L2Expanded)
    rec = float(neighborhood_recall(np.asarray(cand)[:, :k], gt))
    print(f"refine {r}x: recall@{k} = {rec:.4f}")
"""),
    md("""## Serialization

Versioned binary format with backward-compatible loading — see
`raft_tpu/core/serialize.py` for the header layout."""),
    code("""
import io
buf = io.BytesIO()
ivf_pq.save(idx, buf)
buf.seek(0)
idx2 = ivf_pq.load(buf)
print(f"round-trip ok: {idx2.size} rows, {buf.getbuffer().nbytes / 1e6:.1f} MB on disk")
"""),
])


def main():
    out = os.path.join(ROOT, "notebooks")
    os.makedirs(out, exist_ok=True)
    for name, nb in {
        "vector_search_walkthrough.ipynb": VECTOR_SEARCH,
        "ivf_flat_example.ipynb": IVF_FLAT,
        "tutorial_ivf_pq.ipynb": IVF_PQ,
    }.items():
        path = os.path.join(out, name)
        # generated docs, fully reproducible from this script — a torn
        # write is fixed by rerunning, not worth the rename dance
        with open(path, "w") as f:  # graft-lint: ignore[non-atomic-write]
            json.dump(nb, f, indent=1)
            f.write("\n")
        print("wrote", path)


if __name__ == "__main__":
    main()
