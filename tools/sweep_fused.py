"""Dev sweep: fused IVF-Flat scan configs on the 1M x 128 bench shape.

Run EXCLUSIVELY on the TPU (no concurrent processes — tenancy skews
wall-times ~2x). Usage:

    python tools/sweep_fused.py [quick|full]

Prints a QPS/recall table per (merge, extract_every, col_chunk, qt, group,
nprobe) config. Uses the same synthetic clustered data as bench.py.
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax_comp"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from raft_tpu.neighbors import brute_force, ivf_flat  # noqa: E402
from raft_tpu.ops.distance import DistanceType  # noqa: E402
from raft_tpu.stats import neighborhood_recall  # noqa: E402

N, D, NQ, K = 1_000_000, 128, 1024, 10


def timed(fn, nrep=3, inner=4):
    out = fn()
    float(jnp.sum(out[0]))
    best = float("inf")
    for _ in range(nrep):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn()
        float(jnp.sum(out[0]))
        best = min(best, (time.perf_counter() - t0) / inner)
    return best, out


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "quick"
    key = jax.random.PRNGKey(1234)
    kc, ka, kb, kq1, kq2 = jax.random.split(key, 5)
    centers = jax.random.normal(kc, (1000, D), jnp.float32)
    dataset = centers[jax.random.randint(ka, (N,), 0, 1000)] + jax.random.normal(
        kb, (N, D), jnp.float32
    )
    queries = centers[jax.random.randint(kq1, (NQ,), 0, 1000)] + jax.random.normal(
        kq2, (NQ, D), jnp.float32
    )
    float(jnp.sum(dataset[0]))

    t0 = time.perf_counter()
    bf = brute_force.build(dataset, metric=DistanceType.L2Expanded)
    _, ei = brute_force.search(bf, queries, K, query_batch=NQ, dataset_tile=262144)
    gt = np.asarray(ei)
    print(f"# gt in {time.perf_counter()-t0:.1f}s", flush=True)

    if mode == "quick":
        plans = {
            2.0: [
                # (npr, pf, G, qt, merge, ee, cc)   baseline first
                (20, 32, 4, 128, "seg4", 0, 0),
                (20, 32, 4, 128, "bank4", 0, 0),
                (20, 32, 4, 128, "bank8", 0, 1024),
                (20, 32, 8, 128, "bank8", 0, 1024),
            ],
            1.1: [
                (20, 32, 4, 128, "seg4", 0, 0),
                (20, 32, 4, 128, "bank4", 0, 0),
                (20, 32, 4, 128, "bank8", 0, 1024),
                (20, 32, 8, 128, "bank8", 0, 1024),
                (20, 64, 8, 256, "bank8", 0, 1024),
                (20, 32, 16, 128, "bank8", 0, 1024),
                (30, 32, 8, 128, "bank8", 0, 1024),
            ],
        }
    else:
        plans = {
            1.1: [
                (30, 32, 8, 128, "bank8", 0, 1024),
                (30, 24, 8, 256, "bank4", 0, 1024),
                (30, 16, 8, 256, "bank4", 0, 1024),
                (20, 24, 8, 256, "bank4", 0, 1024),
                (30, 24, 8, 512, "bank4", 0, 1024),
                (20, 32, 8, 512, "bank4", 0, 1024),
                (30, 16, 8, 512, "bank4", 0, 1024),
                (50, 16, 8, 256, "bank4", 0, 1024),
            ],
        }

    from _artifact import Recorder

    art = Recorder("sweep_fused", {"n": N, "dim": D, "nq": NQ, "k": K, "mode": mode})
    print(f"# {'config':60s} {'qps':>10s} {'recall':>8s}")
    for cap, configs in plans.items():
        t0 = time.perf_counter()
        fidx = ivf_flat.build(
            dataset,
            ivf_flat.IvfFlatIndexParams(
                n_lists=1024, kmeans_n_iters=10, kmeans_trainset_fraction=0.1,
                list_cap_factor=cap,
            ),
        )
        float(jnp.sum(fidx.list_sizes))
        print(
            f"# cap={cap} build in {time.perf_counter()-t0:.1f}s  max_list={fidx.max_list}",
            flush=True,
        )
        bf16_idx = dataclasses.replace(
            fidx, list_data=fidx.list_data.astype(jnp.bfloat16)
        )
        for npr, pf, g, qt, merge, ee, cc in configs:
            sp = ivf_flat.IvfFlatSearchParams(
                n_probes=npr, fused_qt=qt, fused_probe_factor=pf, fused_group=g,
                fused_merge=merge, fused_precision="default",
                fused_extract_every=ee, fused_col_chunk=cc,
            )
            tag = f"cap={cap} npr={npr} pf={pf} G={g} qt={qt} {merge} ee={ee} cc={cc}"
            try:
                dt, (v, i) = timed(
                    lambda sp=sp: ivf_flat.search(bf16_idx, queries, K, sp, mode="fused")
                )
            except Exception as e:  # noqa: BLE001
                print(f"# {tag:60s} FAILED {type(e).__name__}: {str(e)[:120]}", flush=True)
                continue
            rec = float(neighborhood_recall(np.asarray(i)[:, :K], gt))  # graft-lint: ignore[sync-transfer-in-loop] — post-timed recall readout
            print(f"# {tag:60s} {NQ/dt:>10,.0f} {rec:>8.4f}", flush=True)
            art.add({"config": tag, "qps": round(NQ / dt, 1), "recall": round(rec, 4)})

    art.set_context(device=str(jax.devices()[0]))


if __name__ == "__main__":
    main()
