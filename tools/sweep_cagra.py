"""Dev sweep: CAGRA build (ivf_pq vs nn_descent path) + search configs at
1M x 128. Run EXCLUSIVELY on the TPU: python tools/sweep_cagra.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax_comp"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from raft_tpu.neighbors import brute_force, cagra  # noqa: E402
from raft_tpu.ops.distance import DistanceType  # noqa: E402
from raft_tpu.stats import neighborhood_recall  # noqa: E402

N, D, NQ, K = 1_000_000, 128, 1024, 10


def timed(fn, nrep=3, inner=2):
    out = fn()
    float(jnp.sum(out[0]))
    best = float("inf")
    for _ in range(nrep):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn()
        float(jnp.sum(out[0]))
        best = min(best, (time.perf_counter() - t0) / inner)
    return best, out


def main():
    key = jax.random.PRNGKey(1234)
    kc, ka, kb, kq1, kq2 = jax.random.split(key, 5)
    centers = jax.random.normal(kc, (1000, D), jnp.float32)
    dataset = centers[jax.random.randint(ka, (N,), 0, 1000)] + jax.random.normal(
        kb, (N, D), jnp.float32
    )
    queries = centers[jax.random.randint(kq1, (NQ,), 0, 1000)] + jax.random.normal(
        kq2, (NQ, D), jnp.float32
    )
    float(jnp.sum(dataset[0]))

    bf = brute_force.build(dataset, metric=DistanceType.L2Expanded)
    _, ei = brute_force.search(bf, queries, K, query_batch=NQ, dataset_tile=262144)
    gt = np.asarray(ei)
    print("# gt done", flush=True)

    t0 = time.perf_counter()
    cidx = cagra.build(
        dataset,
        cagra.CagraIndexParams(
            intermediate_graph_degree=32, graph_degree=16, build_algo=cagra.IVF_PQ
        ),
    )
    float(jnp.sum(cidx.graph[0].astype(jnp.float32)))
    build_s = round(time.perf_counter() - t0, 1)
    print(f"# ivf_pq-path build: {build_s}s", flush=True)

    from _artifact import Recorder

    art = Recorder("sweep_cagra", {"n": N, "dim": D, "nq": NQ, "k": K})
    print(f"# {'config':44s} {'qps':>10s} {'recall':>8s}")
    for itopk, w, dedup in [
        (128, 4, "sort"),
        (128, 4, "post"),
        (160, 4, "post"),
        (96, 4, "post"),
        (64, 4, "post"),
        (128, 8, "post"),
        (64, 2, "post"),
    ]:
        sp = cagra.CagraSearchParams(itopk_size=itopk, search_width=w, dedup=dedup)
        tag = f"itopk={itopk} w={w} dedup={dedup}"
        try:
            dt, (v, i) = timed(
                lambda sp=sp: cagra.search(cidx, queries, K, sp)
            )
        except Exception as e:  # noqa: BLE001
            print(f"# {tag:44s} FAILED {type(e).__name__}: {str(e)[:100]}", flush=True)
            continue
        rec = float(neighborhood_recall(np.asarray(i)[:, :K], gt))  # graft-lint: ignore[sync-transfer-in-loop] — post-timed recall readout
        print(f"# {tag:44s} {NQ/dt:>10,.0f} {rec:>8.4f}", flush=True)
        art.add({"config": tag, "qps": round(NQ / dt, 1), "recall": round(rec, 4)})

    # small-batch latency rows (plan_search_params schedule)
    for bq in (1, 10):
        sp = cagra.plan_search_params(bq, K, N, cagra.CagraSearchParams(itopk_size=128, dedup="post"))
        try:
            dt, (v, i) = timed(lambda sp=sp, bq=bq: cagra.search(cidx, queries[:bq], K, sp))
        except Exception as e:  # noqa: BLE001
            print(f"# latency batch={bq} FAILED {type(e).__name__}", flush=True)
            continue
        rec = float(neighborhood_recall(np.asarray(i)[:, :K], gt[:bq]))  # graft-lint: ignore[sync-transfer-in-loop] — post-timed recall readout
        print(f"# latency batch={bq:<3d} {dt*1e3:8.2f} ms  recall={rec:.4f}", flush=True)
        art.add({"config": f"latency batch={bq} w={sp.search_width}",
                 "latency_ms": round(dt * 1e3, 2), "recall": round(rec, 4)})

    art.set_context(build_seconds=build_s, device=str(jax.devices()[0]))


if __name__ == "__main__":
    main()
