#!/usr/bin/env python
"""Summarize raft_tpu.obs artifacts: a metrics JSONL snapshot
(``obs.write_metrics_jsonl``) and/or a Chrome-trace JSON
(``obs.write_trace``).

Usage::

    python tools/obs_report.py bench_artifacts/metrics.jsonl
    python tools/obs_report.py bench_artifacts/trace.json --top 15
    python tools/obs_report.py bench_artifacts/metrics.jsonl bench_artifacts/trace.json

Prints the top spans by **self-time** (wall-clock minus the wall-clock of
nested child spans, computed per thread with a stack sweep — the number
that says where time actually went, not just which outermost span
contained it), then the counter/gauge tables and histogram summaries.

When several files are given, spans and metrics are each taken from the
first file that provides them (a JSONL snapshot and the trace exported
from the same registry describe the same spans — reading both would
double-count). Pure stdlib; safe to run anywhere.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple


def parse_file(path: str) -> Dict[str, Any]:
    """Parse one artifact into ``{"spans": [...], "counters": {...},
    "gauges": {...}, "histograms": {...}}``. JSONL snapshots carry all
    four; Chrome traces carry spans (ph "X") and counters (ph "C")."""
    out: Dict[str, Any] = {
        "spans": [], "counters": {}, "gauges": {}, "histograms": {},
        "spans_dropped": 0,
    }
    if path.endswith(".jsonl"):
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("kind")
                if kind == "span":
                    out["spans"].append(
                        {
                            "name": rec["name"],
                            "ts": float(rec["ts_us"]),
                            "dur": float(rec["dur_us"]),
                            "tid": rec.get("tid", 0),
                            "trace": rec.get("trace") or [],
                        }
                    )
                elif kind in ("counter", "gauge"):
                    out[kind + "s"][_key(rec)] = rec.get("value", 0.0)
                elif kind == "histogram":
                    out["histograms"][_key(rec)] = {
                        "count": rec.get("count", 0),
                        "sum": rec.get("sum", 0.0),
                        "exemplars": rec.get("exemplars") or [],
                    }
        return out
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    out["spans_dropped"] = int(
        (doc.get("otherData") or {}).get("spans_dropped", 0) or 0
    )
    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        if ph == "X":
            args = ev.get("args") or {}
            out["spans"].append(
                {
                    "name": ev["name"],
                    "ts": float(ev["ts"]),
                    "dur": float(ev["dur"]),
                    "tid": ev.get("tid", 0),
                    "trace": args.get("trace") or [],
                }
            )
        elif ph == "C":
            out["counters"][ev["name"]] = ev.get("args", {}).get("value", 0.0)
    return out


#: counter-name prefixes that describe which search kernel/mode a query
#: actually took (includes the rabitq/pq lut labels on ivf_pq.search.*
#: and the mutable delta segment's fused-vs-exact routing)
_DISPATCH_PREFIXES = (
    "ivf_pq.search.",
    "ivf_flat.search.",
    "brute_force.search.",
    "cagra.search.",
    "mutable.delta.",
)

#: prefixes routed to the "robustness & mutability" health table
_HEALTH_PREFIXES = ("robust.", "mutable.", "faults.")

#: distributed-build comm accounting (comms.build.bytes{phase}/
#: comms.build.launches{phase}) — its own table so the CA-vs-full byte
#: savings are visible per build phase, not buried among serving
#: counters
_BUILD_COMMS_PREFIX = "comms.build."

#: serve-side metrics that belong to the health picture, not the
#: generic serving tables (a generation flip is a mutability event the
#: operator correlates with compactions, not with QPS)
_HEALTH_EXTRAS = ("serve.generation_flips",)

#: query-planner metrics (docs/planner.md): per-decision resolutions
#: plus the serving engine's re-plan activity — their own table so a
#: surprising dispatch choice or a flip storm is visible at a glance
_PLANNER_PREFIXES = ("plan.decisions", "serve.plan_flips",
                     "serve.plan.recosts", "serve.plan.epoch")


def _key(rec: Dict[str, Any]) -> str:
    labels = rec.get("labels") or {}
    if not labels:
        return rec["name"]
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{rec['name']}{{{inner}}}"


def self_times(spans: List[Dict[str, Any]]) -> List[Tuple[str, float, float]]:
    """Per-span (name, dur_us, self_us) via a per-tid stack sweep over
    wall-clock containment: a span's self-time is its duration minus the
    durations of the spans directly nested inside it."""
    out: List[Tuple[str, float, float]] = []
    by_tid: Dict[Any, List[Dict[str, Any]]] = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    for tid_spans in by_tid.values():
        # parents first: earlier start, then longer duration on ties
        tid_spans.sort(key=lambda s: (s["ts"], -s["dur"]))
        stack: List[List[Any]] = []  # [end_ts, name, dur, self]
        def flush(upto: float) -> None:
            while stack and stack[-1][0] <= upto:
                end, name, dur, self_us = stack.pop()
                if stack:
                    stack[-1][3] -= dur
                out.append((name, dur, max(self_us, 0.0)))
        for s in tid_spans:
            flush(s["ts"])
            stack.append([s["ts"] + s["dur"], s["name"], s["dur"], s["dur"]])
        flush(float("inf"))
    return out


def aggregate(per_span: List[Tuple[str, float, float]]) -> List[Dict[str, Any]]:
    """Aggregate per-span rows into per-name totals sorted by self-time."""
    agg: Dict[str, Dict[str, Any]] = {}
    for name, dur, self_us in per_span:
        row = agg.setdefault(name, {"name": name, "count": 0, "total_us": 0.0, "self_us": 0.0})
        row["count"] += 1
        row["total_us"] += dur
        row["self_us"] += self_us
    return sorted(agg.values(), key=lambda r: -r["self_us"])


def tail_attribution(
    spans: List[Dict[str, Any]],
    histograms: Dict[str, Dict[str, Any]],
    top: int = 3,
) -> List[Dict[str, Any]]:
    """Attribute the slowest exemplar traces to their per-phase self-time.

    Histogram exemplars name concrete request traces; for the ``top``
    worst (largest exemplar value, deduped by trace ID) this resolves
    each trace's spans and runs the same self-time sweep restricted to
    them, answering "where did THIS p99 request spend its time — queue,
    dispatch, fetch, refine?". Returns one row per trace:
    ``{trace, source, value, dominant, phases: [(name, self_us), ...]}``.
    """
    exemplars: List[Tuple[float, str, str]] = []
    for hname, h in histograms.items():
        for e in h.get("exemplars", []):
            tid = e.get("trace_id")
            if tid:
                exemplars.append((float(e.get("value", 0.0)), str(tid), hname))
    exemplars.sort(key=lambda x: -x[0])
    rows: List[Dict[str, Any]] = []
    seen = set()
    for value, trace_id, hname in exemplars:
        if trace_id in seen:
            continue
        seen.add(trace_id)
        tspans = [s for s in spans if trace_id in (s.get("trace") or [])]
        if not tspans:
            continue
        agg = aggregate(self_times(tspans))
        rows.append(
            {
                "trace": trace_id,
                "source": hname,
                "value": value,
                "dominant": agg[0]["name"],
                "phases": [(r["name"], r["self_us"]) for r in agg],
            }
        )
        if len(rows) >= top:
            break
    return rows


#: robust.breaker.state gauge encoding (see raft_tpu.robust.retry)
_BREAKER_STATES = {0.0: "closed", 1.0: "half_open", 2.0: "open"}


def cluster_health_section(
    gauges: Dict[str, float], health: Optional[Dict[str, Any]] = None
) -> Optional[str]:
    """The "cluster health" section: per-target breaker states decoded
    from the ``robust.breaker.state{target}`` gauges in the artifact,
    plus the aggregated ``ReplicaGroup.health()["cluster"]`` snapshot
    when a health dump is supplied (``--health``). Returns None when
    neither source has anything to say."""
    parts: List[str] = []
    if health:
        cluster = health.get("cluster") or {}
        if cluster:
            rows = [[k, f"{v}" if isinstance(v, str) else f"{v:g}"]
                    for k, v in sorted(cluster.items())]
            parts.append(_table(rows, ["cluster", "value"]))
        replicas = health.get("replicas") or []
        if replicas:
            rows = [
                [str(i), str(r.get("breaker", "?")),
                 f"{r.get('staleness_records', 0):g}",
                 f"{r.get('queue_rows', 0):g}"]
                for i, r in enumerate(replicas)
            ]
            parts.append(_table(rows, ["replica", "breaker", "staleness",
                                       "queue"]))
    breaker_rows = []
    for key, v in sorted(gauges.items()):
        if key.startswith("robust.breaker.state"):
            state = _BREAKER_STATES.get(float(v), f"?{v:g}")
            breaker_rows.append([key, state])
    if breaker_rows:
        parts.append(_table(breaker_rows, ["breaker gauge", "state"]))
    if not parts:
        return None
    return "## cluster health\n" + "\n\n".join(parts)


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))]
    def fmt(r):
        return "  ".join(str(c).ljust(w) if i == 0 else str(c).rjust(w)
                         for i, (c, w) in enumerate(zip(r, widths)))
    lines = [fmt(header), fmt(["-" * w for w in widths])]
    lines += [fmt(r) for r in rows]
    return "\n".join(lines)


def render_report(*paths: str, top: int = 10,
                  plan_explains: Optional[List[str]] = None,
                  health: Optional[Dict[str, Any]] = None) -> str:
    """Build the text report over one or more obs artifact files.

    ``plan_explains`` appends the active query plans' full cost
    breakdowns (``ServingEngine.plan_explain`` /
    ``RegistrationPlan.explain``, see docs/planner.md) as their own
    section, so the report pairs *what dispatched* (the planner metric
    tables) with *why* (the per-candidate cost terms). ``health`` is a
    ``ReplicaGroup.health()`` dump (``--health file.json``) rendered as
    the cluster-health section alongside the breaker-state gauges."""
    spans: List[Dict[str, Any]] = []
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, Dict[str, Any]] = {}
    spans_dropped = 0
    for path in paths:
        if not path:
            continue
        parsed = parse_file(path)
        if parsed["spans"] and not spans:
            spans = parsed["spans"]
        if parsed["counters"] and not counters:
            counters = parsed["counters"]
        if parsed["gauges"] and not gauges:
            gauges = parsed["gauges"]
        if parsed["histograms"] and not histograms:
            histograms = parsed["histograms"]
        spans_dropped = max(spans_dropped, parsed.get("spans_dropped", 0))
    # the counter rides JSONL dumps; otherData rides traces — take either
    spans_dropped = max(
        spans_dropped, int(counters.get("obs.spans_dropped", 0))
    )

    sections: List[str] = ["# obs report"]
    if spans:
        agg = aggregate(self_times(spans))[:top]
        rows = [
            [r["name"], r["count"],
             f"{r['self_us'] / 1e3:.2f}", f"{r['total_us'] / 1e3:.2f}",
             f"{r['total_us'] / 1e3 / r['count']:.2f}"]
            for r in agg
        ]
        section = (f"## top {len(rows)} spans by self-time\n"
                   + _table(rows, ["span", "count", "self_ms", "total_ms", "mean_ms"]))
        if spans_dropped:
            section += (
                f"\n(! {spans_dropped} span(s) dropped at the registry cap — "
                "totals undercount; raise Registry(max_spans=) or reset "
                "between phases)"
            )
        sections.append(section)
    tail = tail_attribution(spans, histograms)
    if tail:
        rows = [
            [r["trace"], r["source"], f"{r['value']:.2f}", r["dominant"],
             "; ".join(f"{n} {s / 1e3:.2f}ms" for n, s in r["phases"][:5])]
            for r in tail
        ]
        sections.append(
            "## tail attribution (slowest exemplar traces)\n"
            + _table(rows, ["trace", "exemplar_of", "value", "dominant", "self-time breakdown"])
        )
    # search-path routing gets its own table: the per-mode dispatch
    # counters (fused / scan / probe, lut="rabitq" vs nibble/f32, the
    # delta segment's fused-vs-exact route) answer the first question a
    # perf investigation asks — "which kernel actually ran?" — including
    # silent fused→XLA fallbacks that only show up as a mode shift here
    dispatch_rows = [
        [k, f"{v:g}"]
        for k, v in sorted(counters.items())
        if k.startswith(_DISPATCH_PREFIXES)
    ]
    if dispatch_rows:
        sections.append("## search dispatch\n"
                        + _table(dispatch_rows, ["counter", "value"]))
    # cluster health: replica breaker states (decoded from the state
    # gauges) and, when a health dump rides along, the replica group's
    # aggregated cluster snapshot — the "is the fleet ok" glance before
    # any per-metric digging
    cluster = cluster_health_section(gauges, health)
    if cluster:
        sections.append(cluster)
    # robustness + mutability get their own table: fault fires, retries,
    # fallbacks, WAL traffic (records/bytes/rotations), tombstone
    # fraction, generations, compaction backlog/heartbeat and serving
    # generation flips — the health picture an operator scans first,
    # pulled out of the generic tables so it cannot drown in per-algo
    # serving counters
    health_rows = [
        [k, kind, f"{v:g}"]
        for kind, table in (("counter", counters), ("gauge", gauges))
        for k, v in sorted(table.items())
        if (k.startswith(_HEALTH_PREFIXES) or k.startswith(_HEALTH_EXTRAS))
        and not k.startswith(_DISPATCH_PREFIXES)
    ]
    if health_rows:
        sections.append("## robustness & mutability\n"
                        + _table(health_rows, ["metric", "kind", "value"]))
    # distributed-build comms: per-phase collective launches and
    # wire-model bytes (kmeans_full vs kmeans_ca vs pq_codebook_*,
    # plus the init-only seed allgather) — the table that SHOWS the
    # communication-avoiding savings instead of just asserting them
    build_rows = [
        [k, f"{v:g}"]
        for k, v in sorted(counters.items())
        if k.startswith(_BUILD_COMMS_PREFIX)
    ]
    if build_rows:
        sections.append("## build comms\n"
                        + _table(build_rows, ["counter", "value"]))
    # query planner: decision resolutions (which engine each "auto"
    # costed out to) and the serving engine's re-plan activity — flips,
    # anchor-refresh recosts, active epochs (docs/planner.md)
    planner_rows = [
        [k, kind, f"{v:g}"]
        for kind, table in (("counter", counters), ("gauge", gauges))
        for k, v in sorted(table.items())
        if k.startswith(_PLANNER_PREFIXES)
    ]
    if planner_rows:
        sections.append("## query planner\n"
                        + _table(planner_rows, ["metric", "kind", "value"]))
    if plan_explains:
        sections.append("## plan explain\n"
                        + "\n\n".join(t.rstrip() for t in plan_explains if t))
    plain = {k: v for k, v in counters.items()
             if not k.startswith(_HEALTH_PREFIXES + _HEALTH_EXTRAS
                                 + _DISPATCH_PREFIXES + _PLANNER_PREFIXES
                                 + (_BUILD_COMMS_PREFIX,))}
    if plain:
        rows = [[k, f"{v:g}"] for k, v in sorted(plain.items())]
        sections.append("## counters\n" + _table(rows, ["counter", "value"]))
    plain_g = {k: v for k, v in gauges.items()
               if not k.startswith(_HEALTH_PREFIXES + _HEALTH_EXTRAS
                                   + _PLANNER_PREFIXES)}
    if plain_g:
        rows = [[k, f"{v:g}"] for k, v in sorted(plain_g.items())]
        sections.append("## gauges\n" + _table(rows, ["gauge", "value"]))
    if histograms:
        rows = [
            [k, h["count"], f"{h['sum'] / h['count']:.3f}" if h["count"] else "-"]
            for k, h in sorted(histograms.items())
        ]
        sections.append("## histograms\n" + _table(rows, ["histogram", "count", "mean"]))
    if len(sections) == 1:
        sections.append("(no spans or metrics found)")
    return "\n\n".join(sections)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="metrics .jsonl and/or Chrome-trace .json files")
    ap.add_argument("--top", type=int, default=10, help="span rows to show")
    ap.add_argument("--plan-explain", metavar="FILE", default=None,
                    help="text file of RegistrationPlan.explain dumps "
                         "(e.g. bench_artifacts/plan_explain.txt) appended "
                         "as the report's plan-explain section")
    ap.add_argument("--health", metavar="FILE", default=None,
                    help="JSON dump of ReplicaGroup.health() rendered as "
                         "the cluster-health section")
    ns = ap.parse_args(argv)
    explains = None
    if ns.plan_explain:
        with open(ns.plan_explain, "r", encoding="utf-8") as f:
            explains = [f.read()]
    health = None
    if ns.health:
        with open(ns.health, "r", encoding="utf-8") as f:
            health = json.load(f)
    try:
        print(render_report(*ns.paths, top=ns.top, plan_explains=explains,
                            health=health))
    except (OSError, json.JSONDecodeError, KeyError, ValueError) as e:
        print(f"obs_report: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
