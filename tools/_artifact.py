"""Timestamped sweep artifacts under ``artifacts/tpu/`` (a TRACKED
directory, unlike ``bench_artifacts/`` which bench.py overwrites): a
wedged chip at round end must not erase mid-round measurements — commit
these as they land.

Use :class:`Recorder` and call ``add(row)`` after EVERY measured config:
the JSON file is rewritten incrementally, so a sweep killed halfway (the
known TPU stall mode) still leaves every completed row on disk."""
import json
import os
import time


class Recorder:
    def __init__(self, name: str, context=None, out_dir=None):
        if out_dir is None:
            root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            out_dir = os.path.join(root, "artifacts", "tpu")
        os.makedirs(out_dir, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        self.path = os.path.join(out_dir, f"{name}_{stamp}.json")
        self.doc = {"name": name, "utc": stamp, "context": context or {}, "rows": []}
        self._flush()
        print(f"# artifact: {self.path}", flush=True)

    def add(self, row) -> None:
        self.doc["rows"].append(row)
        self._flush()

    def set_context(self, **kw) -> None:
        self.doc["context"].update(kw)
        self._flush()

    def _flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.doc, f, indent=2)
        os.replace(tmp, self.path)


def record(name: str, rows, context=None) -> str:
    """One-shot write (kept for completed-sweep callers)."""
    r = Recorder(name, context)
    for row in rows:
        r.add(row)
    return r.path
