#!/usr/bin/env python
"""Render a flight-recorder diagnostic bundle (``*.raftbundle``) as a
post-mortem report.

Usage::

    python tools/bundle_report.py bundle-0001-slo.raftbundle
    python tools/bundle_report.py bundle_dir/            # newest bundle
    python tools/bundle_report.py bundle.raftbundle --json

The bundle is the black box :class:`raft_tpu.obs.recorder.FlightRecorder`
writes on a trigger (SLO alert, fault seam, breaker trip, plan flip,
compactor worker death, or an explicit ``dump()``). This tool answers
the first three incident questions in order: *what tripped* (the
trigger section), *what was the cluster doing* (health + event
timeline), and *where did the slow requests spend their time* (the
exemplar traces, re-attributed with the same self-time sweep
``tools/obs_report.py`` uses).

Loading CRC-verifies the envelope — a torn file is an error, never a
half-read report.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

if __package__ in (None, ""):  # running as `python tools/bundle_report.py`
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

from tools.obs_report import _table, aggregate, self_times


def _fmt_ctx(ctx: Dict[str, Any]) -> str:
    return ", ".join(f"{k}={v}" for k, v in sorted(ctx.items())) or "-"


def _fmt_num(v: Any) -> str:
    try:
        return f"{float(v):g}"
    except (TypeError, ValueError):
        return str(v)


def _trigger_section(bundle: Dict[str, Any]) -> str:
    trig = bundle.get("trigger") or {}
    lines = [
        f"cause:    {trig.get('cause', '?')}",
        f"context:  {_fmt_ctx(trig.get('ctx') or {})}",
        f"at:       t={trig.get('t', 0.0):.3f} (monotonic), "
        f"wall={bundle.get('wall_time', 0.0):.3f}",
        f"window:   last {bundle.get('window_s', 0.0):g}s retained",
    ]
    return "## trigger\n" + "\n".join(lines)


def _health_section(bundle: Dict[str, Any]) -> Optional[str]:
    health = bundle.get("health") or {}
    parts: List[str] = []
    for g in health.get("groups") or []:
        cluster = g.get("cluster") or {}
        if cluster:
            rows = [[k, _fmt_num(v)] for k, v in sorted(cluster.items())]
            parts.append(_table(rows, ["cluster", "value"]))
        replicas = g.get("replicas") or []
        if replicas:
            rows = [
                [str(i), r.get("breaker", "?"),
                 _fmt_num(r.get("staleness_records", 0)),
                 _fmt_num(r.get("queue_rows", r.get("queue_depth", 0)))]
                for i, r in enumerate(replicas)
            ]
            parts.append(
                _table(rows, ["replica", "breaker", "staleness", "queue"])
            )
    for i, e in enumerate(health.get("engines") or []):
        if "error" in e:
            parts.append(f"engine[{i}]: {e['error']}")
            continue
        idx = e.get("indexes") or {}
        rows = []
        for iid, st in sorted(idx.items()):
            slo = st.get("slo") or {}
            slo_cell = (
                f"{'ALERT' if slo.get('alerting') else 'ok'} "
                f"burn={slo.get('burn_fast', 0.0):.2f}" if slo else "-"
            )
            rows.append([iid, str(st.get("algo", "?")),
                         str(st.get("mode", "?")),
                         _fmt_num(st.get("generation", 0)), slo_cell])
        if rows:
            parts.append(_table(rows, [f"engine[{i}] index", "algo",
                                       "mode", "gen", "slo"]))
    if not parts:
        return None
    return "## cluster health\n" + "\n\n".join(parts)


#: event kinds the control plane records (docs/replication.md "The
#: control plane"); pulled out of the timeline into their own section
#: because "who was leader when" is the first question of any
#: replication incident
_CONTROL_KINDS = ("election", "fenced", "scale")


def _control_plane_section(bundle: Dict[str, Any]) -> Optional[str]:
    events = [
        e for e in (bundle.get("events") or [])
        if e.get("kind") in _CONTROL_KINDS
    ]
    if not events:
        return None
    t0 = (bundle.get("trigger") or {}).get("t", 0.0)
    rows = []
    for e in events:
        kind = e.get("kind")
        if kind == "election":
            what = (
                f"epoch {e.get('epoch', '?')} -> leader "
                f"{e.get('leader', '?')} ({e.get('reason', '?')})"
            )
        elif kind == "fenced":
            what = (
                f"{e.get('follower', '?')} rejected epoch "
                f"{e.get('epoch', '?')} (fence at {e.get('fence_epoch', '?')})"
            )
        else:
            what = (
                f"{e.get('group', '?')} scaled {e.get('direction', '?')} "
                f"to {e.get('n_replicas', '?')} replicas"
            )
        rows.append([f"{e.get('t', 0.0) - t0:+.3f}s", str(kind), what])
    return "## control plane (elections / fencing / scaling)\n" + _table(
        rows, ["t-trigger", "kind", "what"]
    )


def _events_section(bundle: Dict[str, Any], limit: int) -> Optional[str]:
    events = bundle.get("events") or []
    if not events:
        return None
    t0 = (bundle.get("trigger") or {}).get("t", 0.0)
    rows = []
    for e in events[-limit:]:
        detail = {k: v for k, v in e.items() if k not in ("t", "kind")}
        rows.append([
            f"{e.get('t', 0.0) - t0:+.3f}s",
            str(e.get("kind", "?")),
            _fmt_ctx(detail),
        ])
    head = f"## event timeline (last {len(rows)} of {len(events)})"
    return head + "\n" + _table(rows, ["t-trigger", "kind", "detail"])


def _series_section(bundle: Dict[str, Any]) -> Optional[str]:
    bank = bundle.get("series") or {}
    series = bank.get("series") or []
    if not series:
        return None
    rows = []
    for s in series:
        labels = s.get("labels") or {}
        key = s["name"] + (
            "{" + ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())) + "}"
            if labels else ""
        )
        pts = s.get("points") or []
        if s.get("kind") == "histogram":
            last = f"count={pts[-1][3]:g}" if pts else "-"
        else:
            last = _fmt_num(pts[-1][1]) if pts else "-"
        span = f"{pts[-1][0] - pts[0][0]:.1f}s" if len(pts) > 1 else "-"
        rows.append([key, s.get("kind", "?"), str(len(pts)), span, last])
    stats = bank.get("stats") or {}
    section = "## retained series\n" + _table(
        rows, ["series", "kind", "points", "span", "last"]
    )
    if stats.get("dropped"):
        section += f"\n(! {stats['dropped']} sample(s) dropped at max_series)"
    return section


def _traces_section(bundle: Dict[str, Any]) -> Optional[str]:
    traces = bundle.get("slow_traces") or []
    if not traces:
        return None
    rows = []
    for t in traces:
        spans = [
            {
                "name": s["name"],
                "ts": float(s.get("ts_us", 0.0)),
                "dur": float(s.get("dur_us", 0.0)),
                "tid": s.get("tid", 0),
                "trace": s.get("trace") or [],
            }
            for s in (t.get("spans") or [])
        ]
        if spans:
            agg = aggregate(self_times(spans))
            dominant = agg[0]["name"]
            chain = " -> ".join(
                s["name"] for s in sorted(spans, key=lambda x: x["ts"])
            )
            breakdown = "; ".join(
                f"{r['name']} {r['self_us'] / 1e3:.2f}ms" for r in agg[:5]
            )
        else:
            dominant, chain, breakdown = "-", "-", "-"
        rows.append([
            str(t.get("trace_id", "?")), f"{float(t.get('value', 0.0)):.2f}",
            dominant, chain, breakdown,
        ])
    return "## slowest traces (exemplars)\n" + _table(
        rows, ["trace", "value", "dominant", "span chain", "self-time"]
    )


def _plans_section(bundle: Dict[str, Any]) -> Optional[str]:
    plans = bundle.get("plans") or {}
    texts = [
        f"--- {iid} ---\n{text}" for iid, text in sorted(plans.items()) if text
    ]
    if not texts:
        return None
    return "## plan explain\n" + "\n\n".join(texts)


def _lockcheck_section(bundle: Dict[str, Any]) -> Optional[str]:
    lc = bundle.get("lockcheck") or {}
    if not lc:
        return None
    cov = lc.get("coverage") or {}
    lines = [
        f"witness:     {'on' if lc.get('enabled') else 'off'}",
        f"edges seen:  {len(lc.get('edges') or [])}",
        f"coverage:    {len(cov.get('exercised') or [])}/"
        f"{len(cov.get('declared') or [])} declared edges exercised",
    ]
    for v in lc.get("violations") or []:
        lines.append(f"VIOLATION:   {v}")
    for v in lc.get("field_violations") or []:
        lines.append(f"FIELD RACE:  {v}")
    return "## lockcheck\n" + "\n".join(lines)


def _fingerprint_section(bundle: Dict[str, Any]) -> Optional[str]:
    fp = bundle.get("fingerprint") or {}
    if not fp:
        return None
    rows = [[k, str(v)] for k, v in sorted(fp.items()) if k != "env"]
    rows += [[f"env.{k}", str(v)] for k, v in sorted((fp.get("env") or {}).items())]
    return "## fingerprint\n" + _table(rows, ["key", "value"])


def render_bundle(bundle: Dict[str, Any], path: str = "",
                  events: int = 40) -> str:
    """The full text report for one loaded bundle dict."""
    title = f"# flight-recorder bundle report"
    if path:
        title += f"\n{path}"
    sections = [title, _trigger_section(bundle)]
    for s in (
        _health_section(bundle),
        _control_plane_section(bundle),
        _events_section(bundle, events),
        _series_section(bundle),
        _traces_section(bundle),
        _plans_section(bundle),
        _lockcheck_section(bundle),
        _fingerprint_section(bundle),
    ):
        if s:
            sections.append(s)
    return "\n\n".join(sections)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="a .raftbundle file, or a directory "
                                 "(renders the newest bundle in it)")
    ap.add_argument("--events", type=int, default=40,
                    help="event-timeline rows to show")
    ap.add_argument("--json", action="store_true",
                    help="dump the decoded bundle body as JSON instead")
    ns = ap.parse_args(argv)

    from raft_tpu.obs import recorder

    path = ns.path
    if os.path.isdir(path):
        bundles = recorder.list_bundles(path)
        if not bundles:
            print(f"bundle_report: no {recorder.BUNDLE_SUFFIX} files in "
                  f"{path}", file=sys.stderr)
            return 1
        path = bundles[-1]
    try:
        bundle = recorder.load_bundle(path)
    except Exception as e:
        print(f"bundle_report: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if ns.json:
        print(json.dumps(bundle, indent=2, sort_keys=True, default=str))
    else:
        print(render_bundle(bundle, path=path, events=ns.events))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
