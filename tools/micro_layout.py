"""Micro-benchmark: per-step cost of the fused-scan block matmul vs layout.

Isolates the kernel's inner step: DMA a [m, d] (row-major) or [d, m]
(dim-major) block, matmul against a [qt, d] query tile, reduce, write.
If the row-major variant is much slower, the main kernel's cost is the
implicit in-kernel transpose of the RHS, not DMA or merge work.
"""
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax_comp"))

QT, D = 128, 128
M = 8704  # rows per block (128-multiple)
N_UNITS = 128
STEPS = 512


def make(layout, reduce_kind):
    def kernel(pr_ref, q_ref, y_ref, out_ref, acc):
        j = pl.program_id(0)

        @pl.when(j == 0)
        def _():
            acc[...] = jnp.zeros((QT, 512), jnp.float32)

        q = q_ref[...]
        if layout == "md":
            y = y_ref[0]  # [M, D]
            dot = lax.dot_general(
                q, y, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            y = y_ref[0]  # [D, M]
            dot = lax.dot_general(
                q, y, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        if reduce_kind == "slice":
            acc[...] = acc[...] + dot[:, :512]
        else:  # banked min over 128-lane groups
            r = dot[:, :512]
            for g in range(1, M // 512):
                r = jnp.minimum(r, dot[:, g * 512 : (g + 1) * 512])
            acc[...] = jnp.minimum(acc[...], r)

        @pl.when(j == STEPS - 1)
        def _():
            out_ref[...] = acc[...]

    block = (1, M, D) if layout == "md" else (1, D, M)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(STEPS,),
        in_specs=[
            pl.BlockSpec((QT, D), lambda j, pr: (0, 0)),
            pl.BlockSpec(block, lambda j, pr: (pr[j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((QT, 512), lambda j, pr: (0, 0)),
        scratch_shapes=[pltpu.VMEM((QT, 512), jnp.float32)],
    )

    @jax.jit
    def run(pr, q, y):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((QT, 512), jnp.float32),
        )(pr, q, y)

    return run


def main():
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (QT, D), jnp.bfloat16)
    pr = jnp.asarray(np.random.default_rng(0).integers(0, N_UNITS, STEPS), jnp.int32)

    for layout in ("md", "dm"):
        shape = (N_UNITS, M, D) if layout == "md" else (N_UNITS, D, M)
        y = jax.random.normal(key, shape, jnp.bfloat16)
        for reduce_kind in ("slice", "min"):
            run = make(layout, reduce_kind)
            out = run(pr, q, y)
            float(jnp.sum(out))
            best = float("inf")
            for _ in range(4):
                t0 = time.perf_counter()
                for _ in range(4):
                    out = run(pr, q, y)
                float(jnp.sum(out))
                best = min(best, (time.perf_counter() - t0) / 4)
            us = best / STEPS * 1e6
            gbps = M * D * 2 / (best / STEPS) / 1e9
            print(f"{layout} {reduce_kind:6s}: {us:8.2f} us/step  ({gbps:6.0f} GB/s eff)")


if __name__ == "__main__":
    main()
