"""Bench-history regression gate: compare the newest BENCH_r*.json
against the prior run and the best run ever recorded.

The driver snapshots every bench invocation as ``BENCH_r<NN>.json`` with
``{n, cmd, rc, tail, parsed}`` where ``tail`` is the (truncated) last
chunk of stdout and ``parsed`` is the headline metric when the run
printed one. The tail usually ends mid-JSON, so rows are recovered by
raw-decoding every ``{"config": ...}`` object that survived the
truncation — partial objects at the cut point are simply skipped.

Comparisons per config row (and for the headline metric):

* qps     — flag when it drops more than ``--qps-drop`` vs the prior
            run, or vs the best-ever value (higher is better),
* p99_ms  — flag when it rises more than ``--p99-rise`` vs prior
            (lower is better; sub-``--ms-floor`` values are noise),
* recall  — flag when it drops more than ``--recall-drop`` absolute.

Exit codes (the CI contract): 0 clean, 1 regression found, 2 not enough
comparable data. ``--smoke`` runs the full pipeline but always exits 0
(unless the tool itself crashes) — that's the ``__graft_entry__``
dryrun wiring, which only wants "the parser still understands the
repo's own BENCH files".
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_RUN_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: per-row metrics we understand: name -> (direction, kind, tolerance)
#: direction +1 = higher is better, -1 = lower is better; ``tolerance``
#: names the argparse knob holding the allowed delta (fractional for
#: "ratio" metrics, absolute for "absolute" ones)
_METRICS = {
    "qps": (+1, "ratio", "qps_drop"),
    "p99_ms": (-1, "ratio", "p99_rise"),
    "recall": (+1, "absolute", "recall_drop"),
    # tiered / tiered_sharded phase columns (bench.py): host-tier fetch
    # traffic and ICI wire traffic regress by growing, overlap by shrinking
    "fetch_bytes_per_query": (-1, "ratio", "bytes_rise"),
    "wire_bytes_per_query": (-1, "ratio", "bytes_rise"),
    "overlap_efficiency": (+1, "absolute", "overlap_drop"),
    # dist_build phase columns (bench.py): per-iteration build-comms
    # traffic regresses by growing, the full:ca reduction by shrinking
    "wire_bytes_per_iter": (-1, "ratio", "bytes_rise"),
    "build_bytes_ratio": (+1, "ratio", "bytes_rise"),
    # planner phase column (bench.py): planner QPS / best hand-tuned QPS
    # at the same recall floor — 1.0 means the cost models found the
    # measured frontier; regresses by dropping
    "planner_regret": (+1, "absolute", "regret_drop"),
    # obs_overhead phase column (bench.py): fractional QPS cost of the
    # always-on recorder + time-series pipeline on the serve row —
    # regresses by growing (absolute: the fraction itself is the delta)
    "recorder_overhead_frac": (-1, "absolute", "overhead_rise"),
    # control_plane phase column (bench.py): the leader-kill failover
    # drill's kill->election window — regresses by growing; rides the
    # p99 tolerance since both are tail-latency-class wall-clock
    "unavailability_ms": (-1, "ratio", "p99_rise"),
}


def discover(bench_dir: str) -> List[Tuple[int, str]]:
    """All ``BENCH_r*.json`` under ``bench_dir``, sorted by run number."""
    out = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = _RUN_RE.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def extract_rows(tail: str) -> List[dict]:
    """Recover complete ``{"config": ...}`` row objects from a
    (possibly mid-JSON-truncated) stdout tail."""
    rows = []
    dec = json.JSONDecoder()
    for m in re.finditer(r'\{"config"', tail):
        try:
            obj, _ = dec.raw_decode(tail, m.start())
        except ValueError:
            continue  # cut off by the tail truncation — not a real row
        if isinstance(obj, dict) and isinstance(obj.get("config"), str):
            rows.append(obj)
    return rows


def load_run(path: str) -> Optional[dict]:
    """One run's comparable surface: ``{n, rc, rows, headline}`` or
    ``None`` when the file is unreadable."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    rows: Dict[str, dict] = {}
    for row in extract_rows(rec.get("tail") or ""):
        key = row["config"]
        i = 2
        while key in rows:  # same config string twice (different section)
            key = f"{row['config']}#{i}"
            i += 1
        rows[key] = row
    return {
        "n": int(rec.get("n", -1)),
        "path": path,
        "rc": int(rec.get("rc", 1)),
        "rows": rows,
        "headline": rec.get("parsed") or None,
    }


def _metric_values(row: dict) -> Dict[str, float]:
    out = {}
    for name in _METRICS:
        v = row.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[name] = float(v)
    return out


def _check(name: str, new: float, ref: float, ref_label: str,
           args) -> Optional[str]:
    """One metric comparison; returns a human-readable regression line
    or ``None`` when within tolerance."""
    direction, kind, tol_name = _METRICS[name]
    tol = getattr(args, tol_name)
    if kind == "absolute":
        drop = ref - new if direction > 0 else new - ref
        if drop > tol:
            return (f"{name} {new:.4f} vs {ref_label} {ref:.4f} "
                    f"(drop {drop:.4f} > {tol:.4f})")
        return None
    if direction > 0:  # qps: flag a fractional drop
        if ref <= 0:
            return None
        drop = 1.0 - new / ref
        if drop > tol:
            return (f"{name} {new:.1f} vs {ref_label} {ref:.1f} "
                    f"(-{drop:.0%} > {tol:.0%})")
        return None
    # lower-is-better ratio (p99, byte counters): flag a fractional rise;
    # wall-clock metrics additionally ignore sub-floor values (timer
    # noise — byte counters are deterministic, so they get no floor)
    if name.endswith("_ms") and ref < args.ms_floor and new < args.ms_floor:
        return None
    if ref <= 0:
        return None
    rise = new / ref - 1.0
    if rise > tol:
        unit = "ms" if name.endswith("_ms") else ""
        return (f"{name} {new:.3f}{unit} vs {ref_label} {ref:.3f}{unit} "
                f"(+{rise:.0%} > {tol:.0%})")
    return None


def compare(runs: List[dict], args) -> Tuple[List[str], int]:
    """Compare the newest clean run against prior + best-ever.

    Returns ``(regression_lines, n_comparisons)``.
    """
    clean = [r for r in runs if r["rc"] == 0 and (r["rows"] or r["headline"])]
    if len(clean) < 2:
        return [], 0
    newest, history = clean[-1], clean[:-1]
    regressions: List[str] = []
    n_cmp = 0

    # -- per-config rows -----------------------------------------------------
    for key, row in sorted(newest["rows"].items()):
        vals = _metric_values(row)
        for name, new_v in sorted(vals.items()):
            refs = []
            # "prior" = most recent older run that measured this config
            for h in reversed(history):
                h_row = h["rows"].get(key)
                if h_row is not None and name in _metric_values(h_row):
                    refs.append((f"prior(r{h['n']:02d})",
                                 _metric_values(h_row)[name]))
                    break
            direction, _, _tol = _METRICS[name]
            hist_vals = [
                (h["n"], _metric_values(h["rows"][key])[name])
                for h in history
                if key in h["rows"] and name in _metric_values(h["rows"][key])
            ]
            if hist_vals:
                best_n, best_v = (max if direction > 0 else min)(
                    hist_vals, key=lambda t: direction * t[1]
                )
                refs.append((f"best(r{best_n:02d})", best_v))
            for ref_label, ref_v in refs:
                n_cmp += 1
                msg = _check(name, new_v, ref_v, ref_label, args)
                if msg:
                    regressions.append(f"[{key}] {msg}")

    # -- headline metric -----------------------------------------------------
    head = newest["headline"]
    if head and isinstance(head.get("value"), (int, float)):
        metric = head.get("metric", "headline")
        hist = [
            (h["n"], float(h["headline"]["value"]))
            for h in history
            if h["headline"] and h["headline"].get("metric") == metric
            and isinstance(h["headline"].get("value"), (int, float))
        ]
        if hist:
            new_v = float(head["value"])
            prior_n, prior_v = hist[-1]
            best_n, best_v = max(hist, key=lambda t: t[1])
            for ref_label, ref_v in (
                (f"prior(r{prior_n:02d})", prior_v),
                (f"best(r{best_n:02d})", best_v),
            ):
                n_cmp += 1
                msg = _check("qps", new_v, ref_v, ref_label, args)
                if msg:
                    regressions.append(f"[headline {metric}] {msg}")
    return regressions, n_cmp


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_regress",
        description="flag bench regressions across BENCH_r*.json history",
    )
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help="directory holding BENCH_r*.json")
    ap.add_argument("--qps-drop", type=float, default=0.25,
                    help="flag qps drops beyond this fraction (default 0.25)")
    ap.add_argument("--p99-rise", type=float, default=0.50,
                    help="flag p99 rises beyond this fraction (default 0.50)")
    ap.add_argument("--recall-drop", type=float, default=0.02,
                    help="flag absolute recall drops beyond this (default 0.02)")
    ap.add_argument("--bytes-rise", type=float, default=0.50,
                    help="flag fetch/wire bytes-per-query rises beyond this "
                         "fraction (default 0.50)")
    ap.add_argument("--overlap-drop", type=float, default=0.25,
                    help="flag absolute overlap_efficiency drops beyond this "
                         "(default 0.25)")
    ap.add_argument("--regret-drop", type=float, default=0.05,
                    help="flag absolute planner_regret drops beyond this "
                         "(default 0.05)")
    ap.add_argument("--overhead-rise", type=float, default=0.02,
                    help="flag absolute recorder_overhead_frac rises beyond "
                         "this (default 0.02 — the <2%% overhead contract)")
    ap.add_argument("--ms-floor", type=float, default=0.05,
                    help="ignore p99 deltas when both sides sit under this")
    ap.add_argument("--smoke", action="store_true",
                    help="parse + compare but always exit 0 (CI dryrun wiring)")
    args = ap.parse_args(argv)

    found = discover(args.dir)
    runs = [r for r in (load_run(p) for _, p in found) if r is not None]
    usable = [r for r in runs if r["rc"] == 0 and (r["rows"] or r["headline"])]
    print(f"bench_regress: {len(found)} BENCH file(s), "
          f"{len(usable)} with comparable data")
    for r in runs:
        tag = "skip (rc!=0)" if r["rc"] != 0 else (
            "skip (no rows)" if not (r["rows"] or r["headline"]) else "ok")
        print(f"  r{r['n']:02d}: rc={r['rc']} rows={len(r['rows'])} "
              f"headline={'yes' if r['headline'] else 'no'} [{tag}]")

    regressions, n_cmp = compare(runs, args)
    if n_cmp == 0:
        if len(usable) < 2:
            print("bench_regress: not enough clean runs (need 2+)")
        else:
            print("bench_regress: no shared config/headline between the "
                  "newest run and history — nothing to gate on")
        return 0 if args.smoke else 2
    newest = usable[-1]
    print(f"bench_regress: r{newest['n']:02d} vs history — "
          f"{n_cmp} comparison(s), {len(regressions)} regression(s)")
    for line in regressions:
        print(f"  REGRESSION {line}")
    if regressions and not args.smoke:
        return 1
    if not regressions:
        print("bench_regress: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
