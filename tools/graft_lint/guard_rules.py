"""Guarded-field checkers: the data-race side of the lock manifest.

The lock-*order* rule (:mod:`tools.graft_lint.concurrency_rules`) says
nothing about the more common race — a shared field read or written
with *no* lock held at all. These rules close that gap, Clang
``GUARDED_BY`` style, driven by the ``[[guards]]`` section of
``lock_order.toml`` (see :class:`tools.graft_lint.lockmanifest.GuardDecl`):

* ``guarded-field``: every access to a declared field — ``self.x`` or
  through a typed receiver (``mut._capture`` where ``mut:
  MutableIndex``) — must be reachable only with the declared lock held.
  Held-lock sets come from the same ``with``-block tracking the
  lock-order rule uses, *plus* an interprocedural entry-held
  must-analysis: a helper whose every (non-fresh) call site holds the
  lock is proven, so ``MutableIndex._apply`` needs no redundant
  re-acquisition. ``write_guarded`` fields check writes only — their
  reads are GIL-atomic single-reference snapshots (the
  bounded-staleness idiom).

* ``guard-inference``: proposes guards for *unannotated* fields that
  are demonstrably shared — written outside construction by code
  reachable from a spawned thread root (``threading.Thread(target=...)``
  sites: the Compactor worker, replica pumps) and also touched from the
  main-thread entry surface. New threaded code gets annotated rather
  than grandfathered.

* ``thread-lifecycle``: every ``threading.Thread(...)`` construction
  must set ``daemon=True`` (a wedged worker must never block
  interpreter exit), and a thread stored on ``self`` must have a
  reachable ``join()`` somewhere on its owning class (the stop/shutdown
  path) — the Compactor and ReplicaGroup pumps are the positive
  examples.

Recognized guarded-field escapes (never reported):

* accesses inside the owning class's own ``__init__`` — the instance
  is not published yet;
* accesses on a *freshly constructed* local instance (``self =
  cls(...)`` in ``MutableIndex.open``, ``mut = MutableIndex(...)`` in a
  helper) — no other thread can hold a reference;
* snapshot-copy-then-act-outside-lock needs no escape: the rule checks
  field *accesses*, and the copy is taken under the lock.

Known limits (documented in docs/static_analysis.md): the entry-held
analysis intersects over *resolved* call sites only — a helper also
reachable through an unresolved callback keeps its proven set
(optimistic); receivers the type inferencer cannot resolve (loop
variables over heterogeneous dicts) are not checked — the runtime field
witness (:mod:`raft_tpu.utils.lockcheck`) closes that gap dynamically.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from tools.graft_lint import lockmanifest
from tools.graft_lint.core import (
    Checker,
    FunctionInfo,
    LintModule,
    LintProject,
    Violation,
    walk_executed,
)
from tools.graft_lint.concurrency_rules import resolve_lock


@dataclasses.dataclass
class FieldAccess:
    """One attribute access on a project-class receiver."""

    cls_name: str                 # receiver class name ("MutableIndex")
    cls_qual: str                 # receiver class qual
    field: str
    kind: str                     # "load" | "store"
    line: int
    col: int
    func: str                     # enclosing function qual
    held: FrozenSet[str]          # locks lexically held at the access
    fresh: bool                   # receiver is a locally constructed instance
    in_own_init: bool             # inside the receiver class's __init__


@dataclasses.dataclass
class GuardFacts:
    """Project-wide field-access and held-lock facts, computed once."""

    accesses: List[FieldAccess]
    #: callee qual -> [(caller qual, held-at-site, fresh-receiver)]
    callsites: Dict[str, List[Tuple[str, FrozenSet[str], bool]]]
    #: resolved threading.Thread targets: qual -> [(module path, line)]
    thread_targets: Dict[str, List[Tuple[str, int]]]
    #: function qual -> locks provably held on EVERY entry
    entry_held: Dict[str, FrozenSet[str]]


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id == "Thread"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "Thread"
    return False


def _thread_target_qual(
    project: LintProject, info: FunctionInfo, call: ast.Call
) -> Optional[str]:
    """Resolve the ``target=`` callable of a Thread construction."""
    for kw in call.keywords:
        if kw.arg != "target":
            continue
        v = kw.value
        if isinstance(v, ast.Attribute):
            recv = project.infer_type(info, v.value)
            if recv is not None:
                return project._lookup_method(recv, v.attr)
        if isinstance(v, ast.Name):
            r = project._resolve_export(info.module.module_name, v.id)
            if r is not None and r[0] == "func":
                return r[1]
    return None


def _fresh_locals(
    project: LintProject, manifest: "lockmanifest.LockManifest", info: FunctionInfo
) -> Dict[str, str]:
    """Local names bound to a freshly constructed instance of a project
    class: ``name -> class qual``. Covers ``self = cls(...)`` inside a
    classmethod constructor (``cls`` builds ``info.cls``)."""
    out: Dict[str, str] = {}
    mod = info.module.module_name
    for node in ast.walk(info.node):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
        ):
            name = node.targets[0].id
            fn = node.value.func
            if isinstance(fn, ast.Name) and fn.id == "cls" and info.cls is not None:
                out[name] = f"{mod}.{info.cls}"
                continue
            cls = project._resolve_value_class(info, fn)
            if cls is not None:
                out[name] = cls
    return out


def guard_facts(
    project: LintProject, manifest: "lockmanifest.LockManifest"
) -> GuardFacts:
    """Compute (and cache on the project) every class-field access with
    its lexically held lock set, every resolved call site with its held
    set, the thread-root set, and the entry-held fixpoint."""
    key = ("guard_facts", manifest.path)
    if key in project._fact_cache:
        return project._fact_cache[key]

    accesses: List[FieldAccess] = []
    callsites: Dict[str, List[Tuple[str, FrozenSet[str], bool]]] = {}
    thread_targets: Dict[str, List[Tuple[str, int]]] = {}

    for qual, info in project.functions.items():
        fresh = _fresh_locals(project, manifest, info)
        _scan_body(
            project, manifest, info, info.node.body, (),
            fresh, accesses, callsites, thread_targets,
        )

    entry_held = _entry_fixpoint(project, callsites, thread_targets)
    facts = GuardFacts(accesses, callsites, thread_targets, entry_held)
    project._fact_cache[key] = facts
    return facts


def _scan_body(
    project, manifest, info, stmts, held, fresh,
    accesses, callsites, thread_targets,
) -> None:
    """Stack walk of a statement list carrying the lexically held lock
    set; recurses into ``with`` bodies with the acquired locks added."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                decl = resolve_lock(
                    project, manifest, info.module, info, item.context_expr
                )
                if decl is not None:
                    new_held.append(decl.name)
            _scan_body(
                project, manifest, info, node.body, tuple(new_held),
                fresh, accesses, callsites, thread_targets,
            )
            continue
        if isinstance(node, ast.Attribute):
            _record_access(project, info, node, held, fresh, accesses)
        elif isinstance(node, ast.Call):
            if _is_thread_ctor(node):
                tq = _thread_target_qual(project, info, node)
                if tq is not None:
                    thread_targets.setdefault(tq, []).append(
                        (info.module.path, node.lineno)
                    )
            target = project.resolve_call(info, node)
            if target is not None:
                recv_fresh = False
                fn = node.func
                if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
                    recv_fresh = fn.value.id in fresh
                callsites.setdefault(target, []).append(
                    (info.qual, frozenset(held), recv_fresh)
                )
        stack.extend(ast.iter_child_nodes(node))


def _record_access(project, info, node, held, fresh, accesses) -> None:
    base = node.value
    cls_qual = None
    is_fresh = False
    if isinstance(base, ast.Name) and base.id in fresh:
        cls_qual = fresh[base.id]
        is_fresh = True
    else:
        cls_qual = project.infer_type(info, base)
    if cls_qual is None or cls_qual not in project.classes:
        return
    cls_name = cls_qual.rsplit(".", 1)[-1]
    kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) else "load"
    accesses.append(
        FieldAccess(
            cls_name=cls_name,
            cls_qual=cls_qual,
            field=node.attr,
            kind=kind,
            line=node.lineno,
            col=node.col_offset + 1,
            func=info.qual,
            held=frozenset(held),
            fresh=is_fresh,
            in_own_init=(
                info.cls is not None
                and info.node.name == "__init__"
                and f"{info.module.module_name}.{info.cls}" == cls_qual
                and isinstance(base, ast.Name)
                and base.id == "self"
            ),
        )
    )


def _entry_fixpoint(project, callsites, thread_targets) -> Dict[str, FrozenSet[str]]:
    """Locks provably held on every entry to each function: the
    intersection over non-fresh call sites of (held at the site ∪ the
    caller's own entry set). Thread targets and functions with no
    resolved call sites start from the empty set (anyone may call them
    with nothing held); the fixpoint only ever shrinks, so it
    converges."""
    universe: FrozenSet[str] = frozenset(
        n for sites in callsites.values() for (_, held, _) in sites for n in held
    )
    entry: Dict[str, FrozenSet[str]] = {}
    for qual in project.functions:
        sites = [s for s in callsites.get(qual, []) if not s[2]]
        if not sites or qual in thread_targets:
            entry[qual] = frozenset()
        else:
            entry[qual] = universe
    changed = True
    while changed:
        changed = False
        for qual in project.functions:
            if not entry[qual]:
                continue
            sites = [s for s in callsites.get(qual, []) if not s[2]]
            if not sites or qual in thread_targets:
                new = frozenset()
            else:
                new = entry[qual]
                for (caller, held, _) in sites:
                    new = new & (held | entry.get(caller, frozenset()))
            if new != entry[qual]:
                entry[qual] = new
                changed = True
    return entry


class GuardedFieldChecker(Checker):
    rule = "guarded-field"
    doc = (
        "access to a lock_order.toml [[guards]] field reachable without "
        "the declared guard held (through the call graph) — a data race; "
        "hold the lock, or declare the idiom"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        manifest = lockmanifest.load_manifest()
        if manifest is None or not manifest.guards:
            return
        project = module.project
        if project is None:
            return
        facts = guard_facts(project, manifest)
        seen: Set[Tuple[int, str, str]] = set()
        for acc in facts.accesses:
            fi = project.functions.get(acc.func)
            if fi is None or fi.module is not module:
                continue
            g = manifest.guard_for(acc.cls_name, acc.field)
            if g is None:
                continue
            decl, mode = g
            if mode == "write" and acc.kind == "load":
                continue
            if acc.fresh or acc.in_own_init:
                continue
            effective = acc.held | facts.entry_held.get(acc.func, frozenset())
            if decl.lock in effective:
                continue
            key = (acc.line, acc.cls_name, acc.field)
            if key in seen:
                continue
            seen.add(key)
            verb = "write to" if acc.kind == "store" else "read of"
            yield Violation(
                rule=self.rule, path=module.path, line=acc.line, col=acc.col,
                message=(
                    f"{verb} '{acc.cls_name}.{acc.field}' without "
                    f"'{decl.lock}' held (guarded by lock_order.toml "
                    f"[[guards]]; reached via {acc.func}) — hold the lock, "
                    "or move the access into construction, or suppress "
                    "with a rationale"
                ),
                witness=(acc.func,),
            )


#: attribute-value constructors that mark a field as synchronization
#: machinery rather than shared data (never an inference candidate)
_SYNC_CTORS = (
    "Lock", "RLock", "Event", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "tracked", "local",
)


class GuardInferenceChecker(Checker):
    rule = "guard-inference"
    doc = (
        "unannotated class field written outside construction by code "
        "reachable from a spawned thread root and touched from the main "
        "entry surface — propose a [[guards]] entry (or suppress with "
        "the lock-free rationale)"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        manifest = lockmanifest.load_manifest()
        if manifest is None:
            return
        project = module.project
        if project is None:
            return
        facts = guard_facts(project, manifest)
        if not facts.thread_targets:
            return
        spawned_reach = self._reach(project, set(facts.thread_targets))
        entries = {
            q for q in project.functions
            if not facts.callsites.get(q) and q not in facts.thread_targets
        }
        main_reach = self._reach(project, entries)
        # spawned root(s) that reach each function, for the message
        root_of: Dict[str, Set[str]] = {}
        for root in facts.thread_targets:
            for q in self._reach(project, {root}):
                root_of.setdefault(q, set()).add(root)

        by_field: Dict[Tuple[str, str], List[FieldAccess]] = {}
        for acc in facts.accesses:
            by_field.setdefault((acc.cls_qual, acc.field), []).append(acc)

        for (cls_qual, field), accs in sorted(by_field.items()):
            ci = project.classes.get(cls_qual)
            if ci is None or ci.module is not module:
                continue
            if manifest.guarded_class(ci.name) is not None:
                continue  # annotated class: guarded-field owns it
            if self._is_sync_field(ci, field):
                continue
            writes = [
                a for a in accs
                if a.kind == "store" and not a.fresh and not a.in_own_init
            ]
            hot = [a for a in writes if a.func in spawned_reach]
            if not hot:
                continue
            touched_main = any(a.func in main_reach for a in accs)
            roots = set()
            for a in accs:
                roots |= root_of.get(a.func, set())
            n_roots = len(roots) + (1 if touched_main else 0)
            if n_roots < 2:
                continue
            a = min(hot, key=lambda x: x.line)
            yield Violation(
                rule=self.rule, path=module.path, line=a.line, col=a.col,
                message=(
                    f"'{ci.name}.{field}' is written outside construction "
                    f"from a spawned thread root ({sorted(roots)[0]}) and "
                    "touched from the main entry surface, but no "
                    "[[guards]] entry covers it — declare its guard in "
                    "lock_order.toml, or suppress with the lock-free "
                    "rationale"
                ),
                witness=(a.func,),
            )

    @staticmethod
    def _reach(project: LintProject, roots: Set[str]) -> Set[str]:
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            q = frontier.pop()
            for _, target in project.calls_of(q):
                if target is not None and target not in seen:
                    seen.add(target)
                    frontier.append(target)
        return seen

    @staticmethod
    def _is_sync_field(ci, field: str) -> bool:
        expr = ci.attr_types.get(field)
        if expr is None:
            return False
        name = None
        while isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        return name in _SYNC_CTORS


class ThreadLifecycleChecker(Checker):
    rule = "thread-lifecycle"
    doc = (
        "threading.Thread constructed without daemon=True, or stored on "
        "an object whose class never join()s it — a wedged or leaked "
        "worker; set the daemon flag and join on the stop/shutdown path"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        project = module.project
        for info in (project.functions.values() if project else []):
            if info.module is not module:
                continue
            for node in walk_executed(info.node.body):
                if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                    continue
                daemon = None
                for kw in node.keywords:
                    if kw.arg == "daemon":
                        daemon = kw.value
                if not (
                    isinstance(daemon, ast.Constant) and daemon.value is True
                ):
                    yield Violation(
                        rule=self.rule, path=module.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            "threading.Thread(...) without daemon=True — a "
                            "wedged worker blocks interpreter exit; mark it "
                            "daemon AND join it on the shutdown path"
                        ),
                    )
                    continue
                if info.cls is not None and not self._class_joins(
                    project, info
                ):
                    yield Violation(
                        rule=self.rule, path=module.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"thread constructed in {info.cls}.{info.node.name} "
                            f"but no method of {info.cls} ever join()s it — "
                            "add a stop()/shutdown() that joins the worker"
                        ),
                    )

    @staticmethod
    def _class_joins(project: LintProject, info: FunctionInfo) -> bool:
        ci = project._mod_classes.get(info.module.module_name, {}).get(info.cls)
        if ci is None:
            return False
        for mq in ci.methods.values():
            fi = project.functions.get(mq)
            if fi is None:
                continue
            for node in walk_executed(fi.node.body):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                ):
                    return True
        return False


def static_guard_status(
    project: LintProject, manifest: "lockmanifest.LockManifest"
) -> Dict[Tuple[str, str], Dict[str, int]]:
    """Per declared guarded field: how many checkable accesses the
    static analysis saw and how many of those it could NOT prove hold
    the guard (escapes and exempt write_guarded reads excluded). The
    ``--graph`` coverage table is built from this: a field is
    statically verified when it has accesses and zero unproven ones;
    zero accesses means the analysis never saw the field at all (a
    declaration typo, or access patterns beyond the type inferencer) —
    only the runtime witness covers it then."""
    facts = guard_facts(project, manifest)
    out: Dict[Tuple[str, str], Dict[str, int]] = {}
    for g in manifest.guards:
        for f in tuple(g.fields) + tuple(g.write_guarded):
            out[(g.cls, f)] = {"accesses": 0, "unheld": 0}
    for acc in facts.accesses:
        gm = manifest.guard_for(acc.cls_name, acc.field)
        if gm is None:
            continue
        decl, mode = gm
        if mode == "write" and acc.kind == "load":
            continue
        if acc.fresh or acc.in_own_init:
            continue
        st = out.setdefault((acc.cls_name, acc.field), {"accesses": 0, "unheld": 0})
        st["accesses"] += 1
        effective = acc.held | facts.entry_held.get(acc.func, frozenset())
        if decl.lock not in effective:
            st["unheld"] += 1
    return out


CHECKERS = [GuardedFieldChecker(), GuardInferenceChecker(), ThreadLifecycleChecker()]
