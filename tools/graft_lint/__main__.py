"""CLI: ``python -m tools.graft_lint [paths...]``.

Exit status: 0 = clean, 1 = violations found, 2 = usage error.

``--json`` emits machine-readable findings — rule, path, line, col,
message, the call-path witness for interprocedural findings, and the
suppression state (suppressed findings are *included* with their flag
set, so the repo gate can pin the suppression count).

``--graph`` skips linting and instead dumps the interprocedural view
the rules run on — the derived lock-acquisition edges (with one call
path witnessing each), a call-graph summary, and the guard-coverage
table (declared vs statically-verified vs runtime-exercised; pass
``--coverage FILE`` with a ``lockcheck.field_coverage()`` JSON dump to
fill the runtime column) — for debugging a surprising finding.

``--infer-guards`` runs only the guard-inference rule and prints a
ready-to-edit ``[[guards]]`` stanza per flagged class.
"""
from __future__ import annotations

import argparse
import json
import re
import sys

from tools.graft_lint.core import all_checkers, load_project, run_lint


def _guard_coverage(project, manifest, coverage: dict) -> list:
    """One row per [[guards]] declaration: declared field counts, the
    static verification verdict, and (when a runtime coverage dump was
    supplied) whether the witness armed and exercised the guard."""
    from tools.graft_lint.guard_rules import static_guard_status

    status = static_guard_status(project, manifest)
    rows = []
    for g in manifest.guards:
        declared = tuple(g.fields) + tuple(g.write_guarded)
        unheld = [f for f in declared if status[(g.cls, f)]["unheld"]]
        unseen = [f for f in declared if not status[(g.cls, f)]["accesses"]]
        runtime = coverage.get(g.cls)
        rows.append({
            "class": g.cls,
            "lock": g.lock,
            "fields": list(g.fields),
            "write_guarded": list(g.write_guarded),
            "statically_verified": not unheld,
            "static_unproven_fields": sorted(unheld),
            "static_unseen_fields": sorted(unseen),
            "runtime": runtime,  # {"armed": bool, "exercised": bool} or None
        })
    return rows


def _infer_guards(paths) -> int:
    """Proposal mode: run only guard-inference and print a skeleton
    [[guards]] stanza per flagged class (lock left for the author)."""
    violations = run_lint(paths, select=["guard-inference"])
    by_class: dict = {}
    for v in violations:
        m = re.search(r"'(\w+)\.(\w+)'", v.message)
        if m:
            by_class.setdefault(m.group(1), []).append((m.group(2), v))
    for v in violations:
        print(v.render())
    for cls in sorted(by_class):
        fields = sorted({f for f, _ in by_class[cls]})
        print()
        print("# proposed — pick the guarding lock and paste into lock_order.toml")
        print("[[guards]]")
        print(f'class = "{cls}"')
        print('lock = "<canonical lock name>"')
        print(f'fields = {json.dumps(fields)}')
    return 1 if violations else 0


def _graph_dump(paths, coverage_path=None) -> dict:
    """The derived graphs as a JSON-ready dict: every resolved call
    edge, and every lock-acquisition fact (function -> lock it may
    acquire, with the call path that witnesses it)."""
    from tools.graft_lint import lockmanifest
    from tools.graft_lint.concurrency_rules import acquired_lock_facts

    project = load_project(paths)
    calls = {}
    for qual in project.functions:
        targets = sorted(
            {t for _, t in project.calls_of(qual) if t is not None}
        )
        if targets:
            calls[qual] = targets
    out = {
        "modules": sorted(m.module_name for m in project.modules),
        "functions": len(project.functions),
        "call_edges": calls,
    }
    manifest = lockmanifest.load_manifest()
    if manifest is not None:
        locks = {}
        lock_edges = set()
        for qual, facts in acquired_lock_facts(project, manifest).items():
            if facts:
                locks[qual] = {
                    name: {"line": ln, "via": path}
                    for name, (ln, path) in sorted(facts.items())
                }
        # held -> acquired pairs actually derivable from nesting: the
        # static analog of what the runtime witness records
        from tools.graft_lint.concurrency_rules import LockOrderChecker

        checker = LockOrderChecker()
        derived = []
        for module in project.modules:
            for v in checker.check(module):
                derived.append(v.render())
        out["lock_order"] = {
            "manifest": manifest.path,
            "declared_edges": sorted(
                f"{a} -> {b}" for (a, b) in manifest.edges
            ),
            "acquires": locks,
            "violations": derived,
        }
        coverage = {}
        if coverage_path:
            with open(coverage_path, "r", encoding="utf-8") as f:
                coverage = json.load(f)
        out["guard_coverage"] = _guard_coverage(project, manifest, coverage)
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graft-lint",
        description="JAX/Pallas static analysis with a VMEM resource model.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["raft_tpu"],
        help="files or directories to lint (default: raft_tpu)",
    )
    parser.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--ignore", metavar="RULES",
        help="comma-separated rule ids to skip",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit violations as JSON"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    parser.add_argument(
        "--graph", action="store_true",
        help="dump the derived call graph, lock-order facts, and the "
             "guard-coverage table as JSON instead of linting",
    )
    parser.add_argument(
        "--coverage", metavar="FILE",
        help="lockcheck field_coverage() JSON dump filling the runtime "
             "column of the --graph guard-coverage table",
    )
    parser.add_argument(
        "--infer-guards", action="store_true",
        help="run only the guard-inference rule and print proposed "
             "[[guards]] stanzas for unannotated shared fields",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for c in all_checkers():
            print(f"{c.rule:16s} {c.doc}")
        return 0

    if args.graph:
        print(json.dumps(
            _graph_dump(args.paths, args.coverage), indent=2, sort_keys=True
        ))
        return 0

    if args.infer_guards:
        return _infer_guards(args.paths)

    try:
        violations = run_lint(
            args.paths,
            select=args.select.split(",") if args.select else None,
            ignore=args.ignore.split(",") if args.ignore else None,
            include_suppressed=args.json,
        )
    except ValueError as e:
        print(f"graft-lint: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([v.as_json() for v in violations], indent=2))
        return 1 if any(not v.suppressed for v in violations) else 0
    for v in violations:
        print(v.render())
    if violations:
        print(f"graft-lint: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
