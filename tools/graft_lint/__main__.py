"""CLI: ``python -m tools.graft_lint [paths...]``.

Exit status: 0 = clean, 1 = violations found, 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import sys

from tools.graft_lint.core import all_checkers, run_lint


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graft-lint",
        description="JAX/Pallas static analysis with a VMEM resource model.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["raft_tpu"],
        help="files or directories to lint (default: raft_tpu)",
    )
    parser.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--ignore", metavar="RULES",
        help="comma-separated rule ids to skip",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit violations as JSON"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for c in all_checkers():
            print(f"{c.rule:16s} {c.doc}")
        return 0

    try:
        violations = run_lint(
            args.paths,
            select=args.select.split(",") if args.select else None,
            ignore=args.ignore.split(",") if args.ignore else None,
        )
    except ValueError as e:
        print(f"graft-lint: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([v.__dict__ for v in violations], indent=2))
    else:
        for v in violations:
            print(v.render())
        if violations:
            print(f"graft-lint: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
