"""CLI: ``python -m tools.graft_lint [paths...]``.

Exit status: 0 = clean, 1 = violations found, 2 = usage error.

``--graph`` skips linting and instead dumps the interprocedural view
the rules run on — the derived lock-acquisition edges (with one call
path witnessing each) and a call-graph summary — as JSON, for
debugging a surprising lock-order or blocking-under-lock finding.
"""
from __future__ import annotations

import argparse
import json
import sys

from tools.graft_lint.core import all_checkers, load_project, run_lint


def _graph_dump(paths) -> dict:
    """The derived graphs as a JSON-ready dict: every resolved call
    edge, and every lock-acquisition fact (function -> lock it may
    acquire, with the call path that witnesses it)."""
    from tools.graft_lint import lockmanifest
    from tools.graft_lint.concurrency_rules import acquired_lock_facts

    project = load_project(paths)
    calls = {}
    for qual in project.functions:
        targets = sorted(
            {t for _, t in project.calls_of(qual) if t is not None}
        )
        if targets:
            calls[qual] = targets
    out = {
        "modules": sorted(m.module_name for m in project.modules),
        "functions": len(project.functions),
        "call_edges": calls,
    }
    manifest = lockmanifest.load_manifest()
    if manifest is not None:
        locks = {}
        lock_edges = set()
        for qual, facts in acquired_lock_facts(project, manifest).items():
            if facts:
                locks[qual] = {
                    name: {"line": ln, "via": path}
                    for name, (ln, path) in sorted(facts.items())
                }
        # held -> acquired pairs actually derivable from nesting: the
        # static analog of what the runtime witness records
        from tools.graft_lint.concurrency_rules import LockOrderChecker

        checker = LockOrderChecker()
        derived = []
        for module in project.modules:
            for v in checker.check(module):
                derived.append(v.render())
        out["lock_order"] = {
            "manifest": manifest.path,
            "declared_edges": sorted(
                f"{a} -> {b}" for (a, b) in manifest.edges
            ),
            "acquires": locks,
            "violations": derived,
        }
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graft-lint",
        description="JAX/Pallas static analysis with a VMEM resource model.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["raft_tpu"],
        help="files or directories to lint (default: raft_tpu)",
    )
    parser.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--ignore", metavar="RULES",
        help="comma-separated rule ids to skip",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit violations as JSON"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    parser.add_argument(
        "--graph", action="store_true",
        help="dump the derived call graph and lock-order facts as JSON "
             "instead of linting",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for c in all_checkers():
            print(f"{c.rule:16s} {c.doc}")
        return 0

    if args.graph:
        print(json.dumps(_graph_dump(args.paths), indent=2, sort_keys=True))
        return 0

    try:
        violations = run_lint(
            args.paths,
            select=args.select.split(",") if args.select else None,
            ignore=args.ignore.split(",") if args.ignore else None,
        )
    except ValueError as e:
        print(f"graft-lint: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([v.__dict__ for v in violations], indent=2))
    else:
        for v in violations:
            print(v.render())
        if violations:
            print(f"graft-lint: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
