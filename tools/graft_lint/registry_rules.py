"""Registry-drift checkers: code-side registries vs. their docs.

Two registries in the tree exist to be *looked up by humans mid-
incident*: the fault-injection seams (``raft_tpu/robust/faults.py:
FAULT_POINTS``) and the metric names the obs layer emits. Both rot the
same way — a seam or metric is added in code, the doc table is not
updated, and six months later the on-call greps for a name that is not
where the runbook says it is. These rules make the drift a lint
failure:

* ``fault-point-drift`` — every seam string in a module-level
  ``FAULT_POINTS`` registry must appear in ``docs/robustness.md`` (the
  seam catalog) and in at least one test under ``tests/`` (excluding
  ``tests/fixtures/`` — a fixture exercising the linter is not a test
  of the seam). An undocumented seam cannot be used in a drill; an
  untested seam is dead chaos code.

* ``metric-drift`` — every metric name passed as a string literal to
  ``obs.inc`` / ``obs.observe`` / ``obs.set_gauge`` must appear in
  ``docs/observability.md``. Dynamic names (variables, f-strings) are
  out of scope — the doc table documents the static namespace.

* ``orphan-span`` — same contract for span names: a string literal
  passed to ``obs.span(...)`` / ``record_span(...)`` must appear in
  the span taxonomy in ``docs/observability.md``. Tail-attribution
  reports and Perfetto traces are read by name; an undocumented span
  is a phase nobody can look up.

* ``unbounded-label`` — a label *value* passed to an emitter must come
  from a bounded domain. The registry keys series by ``(name, labels)``
  (:func:`raft_tpu.obs.metrics._fmt_key`), so a per-request id smuggled
  into a label — an f-string, a raw ``trace_id``/``row_id``/
  ``generation`` — mints a fresh series per call and grows the registry
  (and every ``SeriesBank`` sampling it) without bound. The exemplar
  channel (``observe(..., trace_id=...)``) is the sanctioned way to
  attach high-cardinality ids; it is exempt.

The doc-drift rules locate the repo root by walking up from the linted
file to a directory containing ``docs/``; files outside any such
layout are skipped (the rules are about *this* repo's contract, not a
general property of Python).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Tuple

from tools.graft_lint.core import Checker, LintModule, Violation

#: obs-facade emitters whose first positional argument is a metric name
_EMITTERS = frozenset({"inc", "observe", "set_gauge"})

#: span creators whose first positional argument is a span name
_SPAN_CALLEES = frozenset({"span", "record_span"})


def _repo_root(path: str) -> Optional[str]:
    """Nearest ancestor of ``path`` containing a ``docs`` directory."""
    d = os.path.dirname(os.path.abspath(path))
    while True:
        if os.path.isdir(os.path.join(d, "docs")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


class _DocCorpus:
    """Per-root cached text of doc files and the test corpus."""

    def __init__(self):
        self._docs: Dict[Tuple[str, str], Optional[str]] = {}
        self._tests: Dict[str, str] = {}

    def doc_text(self, root: str, name: str) -> Optional[str]:
        key = (root, name)
        if key not in self._docs:
            p = os.path.join(root, "docs", name)
            try:
                with open(p, "r", encoding="utf-8") as f:
                    self._docs[key] = f.read()
            except OSError:
                self._docs[key] = None
        return self._docs[key]

    def tests_text(self, root: str) -> str:
        if root not in self._tests:
            chunks: List[str] = []
            tests_dir = os.path.join(root, "tests")
            for dirpath, dirnames, filenames in os.walk(tests_dir):
                # a linter fixture mentioning a seam is not a test of it
                dirnames[:] = [d for d in dirnames if d != "fixtures"]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        try:
                            with open(
                                os.path.join(dirpath, fname), "r",
                                encoding="utf-8",
                            ) as f:
                                chunks.append(f.read())
                        except OSError:  # graft-lint: ignore[silent-except] — an unreadable test file just shrinks the corpus
                            pass
            self._tests[root] = "\n".join(chunks)
        return self._tests[root]


_corpus = _DocCorpus()


class FaultPointDriftChecker(Checker):
    rule = "fault-point-drift"
    doc = (
        "FAULT_POINTS seam missing from docs/robustness.md or not "
        "exercised by any test — an undocumented seam cannot be used "
        "in a drill; an untested seam is dead chaos code"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        root = None
        for node in module.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
                for t in targets
            ):
                continue
            value = node.value
            if not isinstance(value, (ast.Tuple, ast.List)) or value is None:
                continue
            if root is None:
                root = _repo_root(module.path)
            if root is None:
                return
            doc = _corpus.doc_text(root, "robustness.md")
            tests = _corpus.tests_text(root)
            for elt in value.elts:
                if not (
                    isinstance(elt, ast.Constant) and isinstance(elt.value, str)
                ):
                    continue
                seam = elt.value
                missing = []
                if doc is None or seam not in doc:
                    missing.append("docs/robustness.md")
                if seam not in tests:
                    missing.append("any test under tests/")
                if missing:
                    yield self.violation(
                        module, elt,
                        f"fault point '{seam}' is missing from "
                        f"{' and from '.join(missing)} — add it to the "
                        "seam catalog and exercise it (an undrillable "
                        "seam is dead chaos code)",
                    )


class MetricDriftChecker(Checker):
    rule = "metric-drift"
    doc = (
        "metric name emitted via obs.inc/observe/set_gauge but absent "
        "from docs/observability.md — the on-call greps the doc table "
        "first; keep it truthful"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        root = None
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name not in _EMITTERS or not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                continue  # dynamic names are out of the static namespace
            metric = arg.value
            if root is None:
                root = _repo_root(module.path) or ""
            if not root:
                return
            doc = _corpus.doc_text(root, "observability.md")
            if doc is None or metric not in doc:
                yield self.violation(
                    module, node,
                    f"metric '{metric}' is not documented in "
                    "docs/observability.md — add a row (name, type, "
                    "labels, meaning) so the emitted namespace and the "
                    "doc table cannot drift",
                )


class OrphanSpanChecker(Checker):
    rule = "orphan-span"
    doc = (
        "span name passed to obs.span/record_span but absent from the "
        "span taxonomy in docs/observability.md — traces and "
        "tail-attribution reports are read by name; an undocumented "
        "span is a phase nobody can look up"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        root = None
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name not in _SPAN_CALLEES or not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                continue  # dynamic span names are out of the static taxonomy
            span_name = arg.value
            if root is None:
                root = _repo_root(module.path) or ""
            if not root:
                return
            doc = _corpus.doc_text(root, "observability.md")
            if doc is None or span_name not in doc:
                yield self.violation(
                    module, node,
                    f"span '{span_name}' is not documented in "
                    "docs/observability.md — add it to the span taxonomy "
                    "(name, phase, what the duration covers) so trace "
                    "readers can look the phase up",
                )


#: identifiers that name per-request / per-row values — a label built
#: from one of these keys a fresh series per call
_UNBOUNDED_IDS = frozenset({
    "trace_id", "trace", "row_id", "rowid", "req_id", "request_id",
    "generation", "seq", "seqno", "seq_no", "uuid", "guid",
})

#: builtins that stringify without bounding the domain
_STRINGIFIERS = frozenset({"str", "repr", "format", "hex"})


def _terminal_id(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class UnboundedLabelChecker(Checker):
    rule = "unbounded-label"
    doc = (
        "label value passed to obs.inc/observe/set_gauge that is "
        "per-request (f-string, trace/row/request id, generation) — "
        "labels key series, so an unbounded value grows the registry "
        "without bound; use the observe(..., trace_id=...) exemplar "
        "channel for high-cardinality ids"
    )

    def _why(self, kw: ast.keyword) -> Optional[str]:
        v = kw.value
        if isinstance(v, ast.JoinedStr) and any(
            isinstance(part, ast.FormattedValue) for part in v.values
        ):
            return "an f-string"
        tid = _terminal_id(v)
        if tid in _UNBOUNDED_IDS:
            return f"the per-request id '{tid}'"
        if isinstance(v, ast.Call):
            fn = v.func
            wraps = (
                isinstance(fn, ast.Name) and fn.id in _STRINGIFIERS
            ) or (isinstance(fn, ast.Attribute) and fn.attr == "format")
            if wraps:
                for arg in list(v.args) + [k.value for k in v.keywords]:
                    tid = _terminal_id(arg)
                    if tid in _UNBOUNDED_IDS:
                        return f"a stringified per-request id '{tid}'"
        return None

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name not in _EMITTERS:
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue  # **labels: dynamic, out of static scope
                if name == "observe" and kw.arg == "trace_id":
                    continue  # the exemplar channel, not a label
                why = self._why(kw)
                if why is not None:
                    yield self.violation(
                        module, kw.value,
                        f"label '{kw.arg}' is {why} — labels key "
                        "series, so a per-request value mints a fresh "
                        "series every call and grows the registry "
                        "without bound; use a bounded enum, or the "
                        "observe(..., trace_id=...) exemplar channel "
                        "for high-cardinality ids",
                    )


CHECKERS = [
    FaultPointDriftChecker(),
    MetricDriftChecker(),
    OrphanSpanChecker(),
    UnboundedLabelChecker(),
]
