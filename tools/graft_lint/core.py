"""graft-lint core: file model, suppression handling, checker registry.

An AST-based static-analysis pass for JAX/Pallas code. The reference
project pairs its kernels with compile-time correctness tooling
(template checks, sanitizer CI); graft-lint is the analog for a traced
Python codebase — it never imports the code under analysis, it parses
it. Three checker families plug in here:

* :mod:`tools.graft_lint.jax_rules` — JAX tracing/correctness lints
  (traced-value branches, numpy calls in jitted paths, static-arg
  declarations, jit-in-loop recompilation hazards, implicit dtypes);
* :mod:`tools.graft_lint.pallas_rules` — a VMEM resource model for
  Pallas kernels (tile alignment, residency budgets, stale hard-coded
  byte budgets);
* :mod:`tools.graft_lint.robust_rules` — fault-visibility lints
  (silently swallowed exceptions).

Suppression syntax (checked against the violation's reported line)::

    x = np.cumsum(h)      # graft-lint: ignore[numpy-in-jit]
    y = risky(x)          # graft-lint: ignore          (all rules)
    # graft-lint: skip-file                             (whole module)

Checkers are approximate by design: they flag patterns that are nearly
always hazards and accept an inline suppression where a human judged
the pattern safe. They must never crash on weird-but-valid code — a
checker that cannot decide stays silent.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

_SUPPRESS_RE = re.compile(
    r"#\s*graft-lint\s*:\s*ignore(?:\[(?P<rules>[\w\-, ]+)\])?"
)
_SKIP_FILE_RE = re.compile(r"#\s*graft-lint\s*:\s*skip-file")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: ``path:line:col: rule-id message``."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Checker:
    """Base checker. Subclasses set ``rule`` (kebab-case id) and ``doc``
    (one-line description for ``--list-rules``/docs) and implement
    :meth:`check` yielding :class:`Violation`."""

    rule: str = ""
    doc: str = ""

    def check(self, module: "LintModule") -> Iterator[Violation]:
        raise NotImplementedError

    def violation(self, module: "LintModule", node: ast.AST, message: str) -> Violation:
        return Violation(
            rule=self.rule,
            path=module.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )


class LintModule:
    """One parsed source file plus its suppression map."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.skip_file = False
        # line -> set of suppressed rule ids; "*" suppresses every rule
        self.suppressions: Dict[int, Set[str]] = {}
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                if _SKIP_FILE_RE.search(tok.string):
                    self.skip_file = True
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = m.group("rules")
                ids = (
                    {r.strip() for r in rules.split(",") if r.strip()}
                    if rules
                    else {"*"}
                )
                self.suppressions.setdefault(tok.start[0], set()).update(ids)
        except tokenize.TokenError:  # graft-lint: ignore[silent-except]
            pass  # partial comment map beats crashing the lint

    def suppressed(self, v: Violation) -> bool:
        ids = self.suppressions.get(v.line, set())
        return "*" in ids or v.rule in ids


def all_checkers() -> List[Checker]:
    """The default checker set, import-cycle-free registry."""
    from tools.graft_lint import comms_rules, jax_rules, pallas_rules, robust_rules

    return [
        *jax_rules.CHECKERS,
        *pallas_rules.CHECKERS,
        *robust_rules.CHECKERS,
        *comms_rules.CHECKERS,
    ]


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    """Expand files/directories to .py files, skipping caches, hidden
    dirs, and generated notebook exports."""
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_source(
    path: str,
    source: str,
    checkers: Optional[Iterable[Checker]] = None,
) -> List[Violation]:
    """Lint one in-memory source buffer. Parse errors surface as a
    single ``parse-error`` violation so broken files fail loudly rather
    than silently passing the gate."""
    try:
        module = LintModule(path, source)
    except SyntaxError as e:
        return [
            Violation(
                rule="parse-error", path=path, line=e.lineno or 1,
                col=(e.offset or 0) + 1 if e.offset else 1,
                message=f"could not parse: {e.msg}",
            )
        ]
    if module.skip_file:
        return []
    out: List[Violation] = []
    for checker in checkers if checkers is not None else all_checkers():
        for v in checker.check(module):
            if not module.suppressed(v):
                out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def run_lint(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    ignore: Optional[Sequence[str]] = None,
) -> List[Violation]:
    """Lint files/directories; returns unsuppressed violations sorted by
    location. ``select``/``ignore`` filter by rule id."""
    checkers = all_checkers()
    if select:
        wanted = set(select)
        unknown = wanted - {c.rule for c in checkers}
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        checkers = [c for c in checkers if c.rule in wanted]
    if ignore:
        checkers = [c for c in checkers if c.rule not in set(ignore)]
    out: List[Violation] = []
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        out.extend(lint_source(path, source, checkers))
    return out
