"""graft-lint core: file model, suppression handling, checker registry.

An AST-based static-analysis pass for JAX/Pallas code. The reference
project pairs its kernels with compile-time correctness tooling
(template checks, sanitizer CI); graft-lint is the analog for a traced
Python codebase — it never imports the code under analysis, it parses
it. Three checker families plug in here:

* :mod:`tools.graft_lint.jax_rules` — JAX tracing/correctness lints
  (traced-value branches, numpy calls in jitted paths, static-arg
  declarations, jit-in-loop recompilation hazards, implicit dtypes);
* :mod:`tools.graft_lint.pallas_rules` — a VMEM resource model for
  Pallas kernels (tile alignment, residency budgets, stale hard-coded
  byte budgets);
* :mod:`tools.graft_lint.robust_rules` — fault-visibility lints
  (silently swallowed exceptions).

Suppression syntax (checked against the violation's reported line)::

    x = np.cumsum(h)      # graft-lint: ignore[numpy-in-jit]
    y = risky(x)          # graft-lint: ignore          (all rules)
    # graft-lint: skip-file                             (whole module)

Checkers are approximate by design: they flag patterns that are nearly
always hazards and accept an inline suppression where a human judged
the pattern safe. They must never crash on weird-but-valid code — a
checker that cannot decide stays silent.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*graft-lint\s*:\s*ignore(?:\[(?P<rules>[\w\-, ]+)\])?"
)
_SKIP_FILE_RE = re.compile(r"#\s*graft-lint\s*:\s*skip-file")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: ``path:line:col: rule-id message``.

    ``witness`` is the call-path evidence for interprocedural findings
    (the function quals the analysis walked through); ``suppressed`` is
    set only when a finding matched an inline suppression and the caller
    asked to see suppressed findings anyway (``--json`` does, so the
    repo gate can pin the suppression count)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    witness: Tuple[str, ...] = ()
    suppressed: bool = False

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_json(self) -> Dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "witness": list(self.witness),
            "suppressed": self.suppressed,
        }


class Checker:
    """Base checker. Subclasses set ``rule`` (kebab-case id) and ``doc``
    (one-line description for ``--list-rules``/docs) and implement
    :meth:`check` yielding :class:`Violation`."""

    rule: str = ""
    doc: str = ""

    def check(self, module: "LintModule") -> Iterator[Violation]:
        raise NotImplementedError

    def violation(self, module: "LintModule", node: ast.AST, message: str) -> Violation:
        return Violation(
            rule=self.rule,
            path=module.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )


class LintModule:
    """One parsed source file plus its suppression map."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.skip_file = False
        # line -> set of suppressed rule ids; "*" suppresses every rule
        self.suppressions: Dict[int, Set[str]] = {}
        #: whole-program view this module was linted under (set by
        #: LintProject); single-file lint_source builds a one-module
        #: project, so checkers can always rely on it
        self.project: Optional["LintProject"] = None
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                if _SKIP_FILE_RE.search(tok.string):
                    self.skip_file = True
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = m.group("rules")
                ids = (
                    {r.strip() for r in rules.split(",") if r.strip()}
                    if rules
                    else {"*"}
                )
                self.suppressions.setdefault(tok.start[0], set()).update(ids)
        except tokenize.TokenError:  # graft-lint: ignore[silent-except]
            pass  # partial comment map beats crashing the lint

    def suppressed(self, v: Violation) -> bool:
        ids = self.suppressions.get(v.line, set())
        return "*" in ids or v.rule in ids


# ---------------------------------------------------------------------------
# Interprocedural layer: a module-resolving call graph over every linted
# file, plus fact propagation ("may block", "may issue a collective",
# "acquires lock L") so checkers can see through calls. Resolution is
# deliberately conservative: a call that cannot be attributed to exactly
# one parsed function stays unresolved, and propagation simply does not
# flow through it — an unknown callee degrades the analysis, never
# crashes it.
# ---------------------------------------------------------------------------

#: call names that block for corpus-proportional (build/save/compile) or
#: device-roundtrip time — the *direct* seeds of the may-block fact
BLOCKING_PRIMITIVES = frozenset(
    {
        # index builds / model fits
        "build", "rebuild", "fit", "_build_main",
        # artifact writes and durability loops
        "atomic_write", "save_path", "save_stream", "_save_rows",
        "_save_main", "_write_generation", "fsync",
        # corpus-proportional filesystem work
        "rmtree",
        # the manifest flip and its wrapper
        "swap", "_publish",
        # device synchronization / transfer
        "block_until_ready", "device_put",
        # host sleeps (retry backoff, injected latency)
        "sleep",
    }
)

#: SPMD collective verbs — every rank in the axis must reach the call
#: the same number of times in the same order or the pod hangs
COLLECTIVE_PRIMITIVES = frozenset(
    {
        "psum", "pmax", "pmin", "pmean", "psum_scatter", "ppermute",
        "all_gather", "all_to_all", "pshuffle",
    }
)


def module_name_for_path(path: str) -> str:
    """Dotted module name derived from the filesystem: walk up while the
    parent directory is a package (has ``__init__.py``). A stray file
    outside any package is just its stem."""
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) or stem


def _last_name(expr: ast.expr) -> Optional[str]:
    """Rightmost name of an expression (``a.b.c`` -> "c")."""
    while isinstance(expr, ast.Call):
        expr = expr.func
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


@dataclasses.dataclass
class FunctionInfo:
    """One parsed function/method in the project."""

    qual: str                      # "pkg.mod.Class.meth" / "pkg.mod.fn"
    module: "LintModule"
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    cls: Optional[str] = None      # enclosing class name, if a method


@dataclasses.dataclass
class _ClassInfo:
    qual: str                      # "pkg.mod.Class"
    name: str
    module: "LintModule"
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, str] = dataclasses.field(default_factory=dict)
    # attribute name -> annotation/constructor expr its type came from
    attr_types: Dict[str, ast.expr] = dataclasses.field(default_factory=dict)


class LintProject:
    """Whole-program view over a set of :class:`LintModule` s: symbol
    tables, import resolution (including package ``__init__``
    re-exports), a call graph, and cycle-safe fact propagation.

    Known limits (documented in ``docs/static_analysis.md``): callables
    passed as values (callbacks, ``retry_call(fn)``) are not tracked;
    receiver types come from ``self``, parameter annotations (string
    annotations and ``Optional[...]`` included), local ``x = Cls(...)``
    assignments, class-body ``self.x`` assignments, and module-global
    instances — anything else leaves the call unresolved.
    """

    def __init__(self, modules: Sequence["LintModule"]):
        self.modules = list(modules)
        self.by_name: Dict[str, LintModule] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, _ClassInfo] = {}
        self._mod_classes: Dict[str, Dict[str, _ClassInfo]] = {}
        self._mod_functions: Dict[str, Dict[str, str]] = {}
        self._mod_imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        self._mod_instances: Dict[str, Dict[str, ast.expr]] = {}
        self._calls: Dict[str, List[Tuple[ast.Call, Optional[str]]]] = {}
        self._fact_cache: Dict[str, Dict] = {}
        self._by_node: Dict[int, FunctionInfo] = {}
        for m in self.modules:
            m.project = self
            m.module_name = module_name_for_path(m.path)
            self.by_name.setdefault(m.module_name, m)
        for m in self.modules:
            try:
                self._index_module(m)
            except Exception:  # graft-lint: ignore[silent-except] — a weird module degrades to "unresolved", never a lint crash
                pass

    # -- indexing ----------------------------------------------------------

    def _index_module(self, m: "LintModule") -> None:
        mod = m.module_name
        classes: Dict[str, _ClassInfo] = {}
        funcs: Dict[str, str] = {}
        imports: Dict[str, Tuple[str, Optional[str]]] = {}
        instances: Dict[str, ast.expr] = {}
        for node in m.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(mod, node, imports)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{mod}.{node.name}"
                funcs[node.name] = qual
                self.functions[qual] = FunctionInfo(qual, m, node)
                self._by_node[id(node)] = self.functions[qual]
            elif isinstance(node, ast.ClassDef):
                ci = _ClassInfo(
                    qual=f"{mod}.{node.name}", name=node.name, module=m,
                    bases=[b for b in (_last_name(x) for x in node.bases) if b],
                )
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        mq = f"{mod}.{node.name}.{sub.name}"
                        ci.methods[sub.name] = mq
                        self.functions[mq] = FunctionInfo(mq, m, sub, cls=node.name)
                        self._by_node[id(sub)] = self.functions[mq]
                        self._scan_self_attrs(sub, ci)
                classes[node.name] = ci
                self.classes[ci.qual] = ci
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and isinstance(node.value, ast.Call):
                    instances[t.id] = node.value.func
            # also index imports/defs nested one level down (e.g. inside
            # ``if TYPE_CHECKING:``) — common enough to matter
            if isinstance(node, (ast.If, ast.Try)):
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, (ast.Import, ast.ImportFrom)):
                        self._index_import(mod, sub, imports)
        self._mod_classes[mod] = classes
        self._mod_functions[mod] = funcs
        self._mod_imports[mod] = imports
        self._mod_instances[mod] = instances

    def _index_import(self, mod, node, imports) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0], None,
                )
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:  # relative: resolve against this package
                pkg = mod.split(".")
                pkg = pkg[: len(pkg) - node.level]
                base = ".".join(pkg + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                imports[a.asname or a.name] = (base, a.name)

    def _scan_self_attrs(self, fn: ast.AST, ci: _ClassInfo) -> None:
        """Record ``self.x: T = ...`` / ``self.x = Cls(...)`` attribute
        types from method bodies (``__init__`` mostly)."""
        for node in ast.walk(fn):
            target = value = None
            if isinstance(node, ast.AnnAssign) and node.annotation is not None:
                target, value = node.target, node.annotation
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                if isinstance(node.value, ast.Call):
                    target, value = node.targets[0], node.value.func
            if (
                target is not None
                and isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and target.attr not in ci.attr_types
            ):
                ci.attr_types[target.attr] = value

    # -- symbol / type resolution ------------------------------------------

    def _resolve_export(self, mod: str, name: str, _depth=0):
        """What ``mod.name`` is: ("func"|"class"|"module", qual) or
        None. Follows ``from x import y`` re-export chains (package
        ``__init__`` facades)."""
        if _depth > 8:
            return None
        if f"{mod}.{name}" in self.by_name:
            return ("module", f"{mod}.{name}")
        if mod not in self.by_name:
            return None
        if name in self._mod_functions.get(mod, {}):
            return ("func", self._mod_functions[mod][name])
        if name in self._mod_classes.get(mod, {}):
            return ("class", self._mod_classes[mod][name].qual)
        imp = self._mod_imports.get(mod, {}).get(name)
        if imp is not None:
            base, sym = imp
            if sym is None:
                return ("module", base) if base in self.by_name else None
            return self._resolve_export(base, sym, _depth + 1)
        if name in self._mod_instances.get(mod, {}):
            cls = self._resolve_class_expr(mod, self._mod_instances[mod][name])
            if cls is not None:
                return ("instance", cls)
        return None

    def _resolve_class_expr(self, mod: str, expr) -> Optional[str]:
        """Resolve a type-ish expression (``Name``, ``a.B``, a string
        annotation, ``Optional[T]``, ``T | None``) to a class qual."""
        if expr is None:
            return None
        if isinstance(expr, str):
            try:
                expr = ast.parse(expr, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return self._resolve_class_expr(mod, expr.value)
        if isinstance(expr, ast.Subscript):  # Optional[T] / List[T] — inner
            return self._resolve_class_expr(mod, expr.slice)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.BitOr):
            return (
                self._resolve_class_expr(mod, expr.left)
                or self._resolve_class_expr(mod, expr.right)
            )
        if isinstance(expr, ast.Name):
            if expr.id == "None":
                return None
            r = self._resolve_export(mod, expr.id)
            return r[1] if r is not None and r[0] == "class" else None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            r = self._resolve_export(mod, expr.value.id)
            if r is not None and r[0] == "module":
                r2 = self._resolve_export(r[1], expr.attr)
                return r2[1] if r2 is not None and r2[0] == "class" else None
        return None

    def _class_info(self, cls_qual: str) -> Optional[_ClassInfo]:
        return self.classes.get(cls_qual)

    def _lookup_method(self, cls_qual: str, name: str, _depth=0) -> Optional[str]:
        ci = self.classes.get(cls_qual)
        if ci is None or _depth > 8:
            return None
        if name in ci.methods:
            return ci.methods[name]
        for b in ci.bases:  # by-name base lookup within the same module
            r = self._resolve_export(ci.module.module_name, b)
            if r is not None and r[0] == "class":
                m = self._lookup_method(r[1], name, _depth + 1)
                if m is not None:
                    return m
        return None

    def infer_type(self, info: FunctionInfo, expr: ast.expr) -> Optional[str]:
        """Class qual of the value ``expr`` evaluates to inside
        ``info``'s body, or None."""
        mod = info.module.module_name
        if isinstance(expr, ast.Name):
            if expr.id == "self" and info.cls is not None:
                return f"{mod}.{info.cls}"
            ann = self._param_annotation(info, expr.id)
            if ann is not None:
                return self._resolve_class_expr(mod, ann)
            local = self._local_ctor(info, expr.id)
            if local is not None:
                return self._resolve_class_expr(mod, local)
            r = self._resolve_export(mod, expr.id)
            if r is not None and r[0] == "instance":
                return r[1]
            return None
        if isinstance(expr, ast.Attribute):
            base = self.infer_type(info, expr.value)
            if base is not None:
                ci = self._class_info(base)
                seen = set()
                while ci is not None and ci.qual not in seen:
                    seen.add(ci.qual)
                    if expr.attr in ci.attr_types:
                        return self._resolve_class_expr(
                            ci.module.module_name, ci.attr_types[expr.attr]
                        )
                    nxt = None
                    for b in ci.bases:
                        r = self._resolve_export(ci.module.module_name, b)
                        if r is not None and r[0] == "class":
                            nxt = self._class_info(r[1])
                            break
                    ci = nxt
            return None
        if isinstance(expr, ast.Call):
            cls = None
            if isinstance(expr.func, (ast.Name, ast.Attribute)):
                cls = self._resolve_value_class(info, expr.func)
            return cls
        return None

    def _resolve_value_class(self, info, func_expr) -> Optional[str]:
        """``Cls(...)`` constructor expression -> class qual."""
        mod = info.module.module_name
        if isinstance(func_expr, ast.Name):
            r = self._resolve_export(mod, func_expr.id)
            return r[1] if r is not None and r[0] == "class" else None
        if isinstance(func_expr, ast.Attribute) and isinstance(func_expr.value, ast.Name):
            r = self._resolve_export(mod, func_expr.value.id)
            if r is not None and r[0] == "module":
                r2 = self._resolve_export(r[1], func_expr.attr)
                return r2[1] if r2 is not None and r2[0] == "class" else None
        return None

    def _param_annotation(self, info: FunctionInfo, name: str):
        args = getattr(info.node, "args", None)
        if args is None:
            return None
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.arg == name:
                return a.annotation
        return None

    def _local_ctor(self, info: FunctionInfo, name: str):
        """The ``Cls(...)`` ctor expression a local name was assigned
        from (first match wins; cached per function)."""
        cache = self._fact_cache.setdefault("_local_ctors", {})
        if info.qual not in cache:
            ctors = {}
            for node in ast.walk(info.node):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and node.targets[0].id not in ctors
                ):
                    ctors[node.targets[0].id] = node.value.func
            cache[info.qual] = ctors
        return cache[info.qual].get(name)

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, info: FunctionInfo, call: ast.Call) -> Optional[str]:
        """Qualified name of the single parsed function this call can
        reach, or None (unknown callee — propagation stops here)."""
        try:
            return self._resolve_call(info, call)
        except Exception:  # graft-lint: ignore[silent-except] — resolution must never crash the lint; unresolved is the safe answer
            return None

    def _resolve_call(self, info: FunctionInfo, call: ast.Call) -> Optional[str]:
        mod = info.module.module_name
        fn = call.func
        if isinstance(fn, ast.Name):
            r = self._resolve_export(mod, fn.id)
            if r is None:
                return None
            if r[0] == "func":
                return r[1]
            if r[0] == "class":
                return self._lookup_method(r[1], "__init__")
            return None
        if isinstance(fn, ast.Attribute):
            # module-qualified chains: seg.WriteAheadLog.open, obs.inc
            if isinstance(fn.value, ast.Name):
                r = self._resolve_export(mod, fn.value.id)
                if r is not None and r[0] == "module":
                    r2 = self._resolve_export(r[1], fn.attr)
                    if r2 is not None and r2[0] == "func":
                        return r2[1]
                    if r2 is not None and r2[0] == "class":
                        return self._lookup_method(r2[1], "__init__")
                    if r2 is not None and r2[0] == "instance":
                        return None  # bare instance, no method — unreachable
                    return None
                if r is not None and r[0] == "instance":
                    return self._lookup_method(r[1], fn.attr)
            elif (
                isinstance(fn.value, ast.Attribute)
                and isinstance(fn.value.value, ast.Name)
            ):
                r = self._resolve_export(mod, fn.value.value.id)
                if r is not None and r[0] == "module":
                    r2 = self._resolve_export(r[1], fn.value.attr)
                    if r2 is not None and r2[0] == "class":
                        return self._lookup_method(r2[1], fn.attr)
                    if r2 is not None and r2[0] == "instance":
                        cls = self._class_info(r2[1])
                        if cls is not None and fn.attr in cls.attr_types:
                            pass  # attr of an instance: fall through to type inference
            # receiver-typed resolution: self.m(), mut.wal.append(), ...
            recv = self.infer_type(info, fn.value)
            if recv is not None:
                return self._lookup_method(recv, fn.attr)
        return None

    def calls_of(self, qual: str) -> List[Tuple[ast.Call, Optional[str]]]:
        """Every call expression in ``qual``'s body (nested def/lambda
        bodies excluded — deferred code) with its resolved target."""
        if qual in self._calls:
            return self._calls[qual]
        info = self.functions.get(qual)
        out: List[Tuple[ast.Call, Optional[str]]] = []
        if info is not None:
            for node in walk_executed(info.node.body):
                if isinstance(node, ast.Call):
                    out.append((node, self.resolve_call(info, node)))
        self._calls[qual] = out
        return out

    # -- fact propagation --------------------------------------------------

    def propagate(self, direct) -> Dict[str, Dict]:
        """Cycle-safe fixpoint: ``direct(info)`` maps a function to its
        locally-established facts ``{key: line}``; the result maps every
        function to ``{key: (line, call_path)}`` where ``call_path`` is
        the qual chain (possibly empty) from that function to the one
        holding the fact directly. Recursion converges because facts
        only accumulate."""
        facts: Dict[str, Dict] = {}
        for qual, info in self.functions.items():
            try:
                facts[qual] = {k: (ln, []) for k, ln in direct(info).items()}
            except Exception:  # graft-lint: ignore[silent-except] — one weird function must not sink the whole pass
                facts[qual] = {}
        changed = True
        while changed:
            changed = False
            for qual in self.functions:
                mine = facts[qual]
                for _, target in self.calls_of(qual):
                    if target is None or target == qual:
                        continue
                    for key, (ln, path) in facts.get(target, {}).items():
                        if key not in mine:
                            mine[key] = (ln, [target] + path)
                            changed = True
        return facts

    def blocking_facts(self) -> Dict[str, Dict]:
        """function qual -> {(container_qual, primitive): (line, path)}.
        The key keeps the primitive *and* the function that calls it
        directly, so an allow-list can excuse one durability path (WAL
        fsync) without excusing every fsync in the program."""
        if "blocking" not in self._fact_cache:
            def direct(info: FunctionInfo):
                out = {}
                for node in walk_executed(info.node.body):
                    if isinstance(node, ast.Call):
                        name = _last_name(node.func)
                        if name in BLOCKING_PRIMITIVES:
                            out[(info.qual, name)] = node.lineno
                return out
            self._fact_cache["blocking"] = self.propagate(direct)
        return self._fact_cache["blocking"]

    def collective_facts(self) -> Dict[str, Dict]:
        """function qual -> {collective_name: (line, path)} — which SPMD
        collectives the function may issue, directly or transitively."""
        if "collective" not in self._fact_cache:
            def direct(info: FunctionInfo):
                out = {}
                for node in walk_executed(info.node.body):
                    if isinstance(node, ast.Call):
                        name = _last_name(node.func)
                        if name in COLLECTIVE_PRIMITIVES:
                            out.setdefault(name, node.lineno)
                return out
            self._fact_cache["collective"] = self.propagate(direct)
        return self._fact_cache["collective"]

    def function_at(self, module: "LintModule", node: ast.AST) -> Optional[FunctionInfo]:
        """The FunctionInfo whose def node is ``node`` in ``module``."""
        return self._by_node.get(id(node))


def walk_executed(stmts):
    """Walk statements without descending into nested def/lambda bodies
    — deferred code does not run at the point it is defined."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def all_checkers() -> List[Checker]:
    """The default checker set, import-cycle-free registry."""
    from tools.graft_lint import (
        comms_rules,
        concurrency_rules,
        dispatch_rules,
        guard_rules,
        jax_rules,
        pallas_rules,
        registry_rules,
        robust_rules,
    )

    return [
        *jax_rules.CHECKERS,
        *pallas_rules.CHECKERS,
        *robust_rules.CHECKERS,
        *comms_rules.CHECKERS,
        *concurrency_rules.CHECKERS,
        *guard_rules.CHECKERS,
        *registry_rules.CHECKERS,
        *dispatch_rules.CHECKERS,
    ]


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    """Expand files/directories to .py files, skipping caches, hidden
    dirs, and generated notebook exports."""
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def _check_module(
    module: LintModule,
    checkers: Optional[Iterable[Checker]],
    include_suppressed: bool = False,
) -> List[Violation]:
    out: List[Violation] = []
    for checker in checkers if checkers is not None else all_checkers():
        for v in checker.check(module):
            if not module.suppressed(v):
                out.append(v)
            elif include_suppressed:
                out.append(dataclasses.replace(v, suppressed=True))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def select_checkers(
    select: Optional[Sequence[str]] = None,
    ignore: Optional[Sequence[str]] = None,
) -> List[Checker]:
    """The default checker set filtered by rule id; unknown ids in
    ``select`` raise (a typo'd gate must fail loudly)."""
    checkers = all_checkers()
    if select:
        wanted = set(select)
        unknown = wanted - {c.rule for c in checkers}
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        checkers = [c for c in checkers if c.rule in wanted]
    if ignore:
        checkers = [c for c in checkers if c.rule not in set(ignore)]
    return checkers


def lint_project(
    project: "LintProject",
    select: Optional[Sequence[str]] = None,
    ignore: Optional[Sequence[str]] = None,
    include_suppressed: bool = False,
) -> List[Violation]:
    """Lint an already-built whole-program project. This is the repo
    gate's entry point: the expensive part of a lint run is building the
    project (parsing every file, indexing symbols), so the gate builds
    it once and runs each rule family's strict pass over the same
    project — interprocedural fact caches carry over too."""
    checkers = select_checkers(select, ignore)
    out: List[Violation] = []
    for module in project.modules:
        if module.skip_file:
            continue
        out.extend(_check_module(module, checkers, include_suppressed))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def lint_source(
    path: str,
    source: str,
    checkers: Optional[Iterable[Checker]] = None,
) -> List[Violation]:
    """Lint one in-memory source buffer (as a one-module project, so
    interprocedural rules see intra-module calls). Parse errors surface
    as a single ``parse-error`` violation so broken files fail loudly
    rather than silently passing the gate."""
    try:
        module = LintModule(path, source)
    except SyntaxError as e:
        return [
            Violation(
                rule="parse-error", path=path, line=e.lineno or 1,
                col=(e.offset or 0) + 1 if e.offset else 1,
                message=f"could not parse: {e.msg}",
            )
        ]
    if module.skip_file:
        return []
    LintProject([module])
    return _check_module(module, checkers)


def load_project(paths: Sequence[str]) -> "LintProject":
    """Parse every .py under ``paths`` into one whole-program
    :class:`LintProject` (unparseable files are dropped here — ``run_lint``
    reports them separately)."""
    modules: List[LintModule] = []
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            modules.append(LintModule(path, source))
        except (OSError, UnicodeDecodeError, SyntaxError):
            continue
    return LintProject(modules)


def run_lint(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    ignore: Optional[Sequence[str]] = None,
    include_suppressed: bool = False,
) -> List[Violation]:
    """Lint files/directories as one whole-program project; returns
    unsuppressed violations sorted by location. ``select``/``ignore``
    filter by rule id; ``include_suppressed`` keeps suppressed findings
    in the output with their flag set (machine consumers)."""
    checkers = select_checkers(select, ignore)
    out: List[Violation] = []
    modules: List[LintModule] = []
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            modules.append(LintModule(path, source))
        except SyntaxError as e:
            out.append(
                Violation(
                    rule="parse-error", path=path, line=e.lineno or 1,
                    col=(e.offset or 0) + 1 if e.offset else 1,
                    message=f"could not parse: {e.msg}",
                )
            )
    LintProject(modules)  # sets module.project on every module
    for module in modules:
        if module.skip_file:
            continue
        out.extend(_check_module(module, checkers, include_suppressed))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out
