"""graft-lint — static analysis for JAX/Pallas code with a VMEM
resource model.

Usage::

    python -m tools.graft_lint raft_tpu/          # lint a tree
    python -m tools.graft_lint --list-rules       # what gets checked

Library API: :func:`run_lint` / :func:`lint_source` return
:class:`Violation` lists; the tier-1 suite runs the former over
``raft_tpu/`` (``tests/test_graft_lint_repo.py``) so any unsuppressed
violation fails CI. See ``docs/static_analysis.md``.
"""
from tools.graft_lint.core import (
    Checker,
    LintModule,
    Violation,
    all_checkers,
    lint_source,
    run_lint,
)

__all__ = [
    "Checker",
    "LintModule",
    "Violation",
    "all_checkers",
    "lint_source",
    "run_lint",
]
