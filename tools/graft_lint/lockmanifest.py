"""The declared lock-order manifest (``lock_order.toml``).

The concurrency contract of the serving stack used to live in one
comment (``segments.py``: "_compact_mutex strictly before _lock").
``lock_order.toml`` is that contract made machine-checkable: every
tracked lock gets a canonical name, an attribute spelling, and the
class/path context that disambiguates the five different ``_lock``
attributes in the tree; every *permitted* acquisition edge (lock held →
lock acquired) is declared explicitly with a rationale. Two consumers
read it:

* the static ``lock-order`` rule (:mod:`tools.graft_lint.concurrency_rules`)
  derives the actual edge set from the call graph and reports any edge
  the manifest does not permit (an inversion of a declared edge is a
  potential deadlock; a novel edge is manifest drift);
* the runtime lock-witness (:mod:`raft_tpu.utils.lockcheck`) records the
  edges real threads take under chaos and asserts each against the same
  declarations, so the static graph can never silently rot.

``may_block`` marks a lock whose holders are *expected* to block (the
compaction mutex serializes whole rebuilds; nobody latency-sensitive
contends on it), exempting it from ``blocking-under-lock``.
``[[allow_blocking]]`` entries excuse one named callee (suffix match on
the qualified name) under one named lock — the durable-then-visible WAL
fsync is the canonical example: blocking, under ``_lock``, and the
whole point.

Parsing prefers :mod:`tomllib`/:mod:`tomli`; a dependency-free subset
parser (tables-of-arrays with string/bool/string-array values — exactly
what the manifest uses) keeps the linter runnable without either.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

DEFAULT_MANIFEST_PATH = os.path.join(os.path.dirname(__file__), "lock_order.toml")


def parse_toml_subset(text: str) -> dict:
    """Parse the TOML subset the manifest uses: top-level ``key = value``
    pairs and ``[[array.of.tables]]`` sections, with string, boolean,
    and string-array values."""
    root: dict = {}
    current = root

    def _value(raw: str):
        raw = raw.strip()
        if raw.startswith("["):
            inner = raw.strip()[1:-1]
            items = []
            for part in inner.split(","):
                part = part.strip()
                if part:
                    items.append(_value(part))
            return items
        if raw.startswith('"') and raw.endswith('"'):
            return raw[1:-1]
        if raw in ("true", "false"):
            return raw == "true"
        try:
            return int(raw)
        except ValueError:
            return raw

    for line in text.splitlines():
        # strip comments outside strings (manifest strings carry no '#')
        if "#" in line:
            line = line.split("#", 1)[0]
        line = line.strip()
        if not line:
            continue
        if line.startswith("[[") and line.endswith("]]"):
            name = line[2:-2].strip()
            current = {}
            root.setdefault(name, []).append(current)
            continue
        if line.startswith("[") and line.endswith("]"):
            name = line[1:-1].strip()
            current = root.setdefault(name, {})
            continue
        if "=" in line:
            key, raw = line.split("=", 1)
            current[key.strip()] = _value(raw)
    return root


def _load_toml(path: str) -> dict:
    with open(path, "rb") as f:
        data = f.read()
    text = data.decode("utf-8")
    try:
        import tomllib  # Python >= 3.11
    except ImportError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ImportError:
            return parse_toml_subset(text)
    return tomllib.loads(text)


@dataclasses.dataclass(frozen=True)
class LockDecl:
    """One tracked lock: canonical name + how to recognize it."""

    name: str                     # canonical id, e.g. "mutable.lock"
    attr: str                     # attribute spelling, e.g. "_lock"
    classes: Tuple[str, ...]      # owning class names ("" for module-level)
    where: Tuple[str, ...]        # path prefixes it lives under
    may_block: bool = False       # holders are expected to block


@dataclasses.dataclass(frozen=True)
class GuardDecl:
    """One ``[[guards]]`` entry: which fields of a class a lock guards.

    ``fields`` are fully guarded — every read and write must hold the
    lock. ``write_guarded`` fields serialize *writers* under the lock
    but allow lock-free reads: single reference/int fields whose reads
    are atomic under the GIL and whose readers tolerate one-write-stale
    values (the bounded-staleness snapshot idiom — e.g. a replication
    tick reading ``leader.wal``). Both the static ``guarded-field`` rule
    and the runtime field witness enforce exactly these semantics."""

    cls: str                      # owning class name, e.g. "MutableIndex"
    lock: str                     # guarding lock's canonical name
    fields: Tuple[str, ...]       # reads AND writes require the lock
    write_guarded: Tuple[str, ...] = ()  # only writes require the lock
    where: Tuple[str, ...] = ()   # path prefixes (doc/debug aid)
    why: str = ""


class LockManifest:
    """Parsed ``lock_order.toml``: lock declarations, the permitted
    acquisition-edge set, and the blocking allow-list."""

    def __init__(self, data: dict, path: str = DEFAULT_MANIFEST_PATH):
        self.path = path
        self.locks: Dict[str, LockDecl] = {}
        self.scan: Tuple[str, ...] = tuple(data.get("scan", []))
        for entry in data.get("lock", []):
            decl = LockDecl(
                name=entry["name"],
                attr=entry["attr"],
                classes=tuple(entry.get("classes", [])),
                where=tuple(entry.get("where", [])),
                may_block=bool(entry.get("may_block", False)),
            )
            self.locks[decl.name] = decl
        self.edges: Dict[Tuple[str, str], str] = {}
        for entry in data.get("edge", []):
            self.edges[(entry["from"], entry["to"])] = entry.get("why", "")
        self.allow_blocking: List[Tuple[str, str, str]] = [
            (e["lock"], e["callee"], e.get("why", ""))
            for e in data.get("allow_blocking", [])
        ]
        self.guards: List[GuardDecl] = []
        for entry in data.get("guards", []):
            self.guards.append(
                GuardDecl(
                    cls=entry["class"],
                    lock=entry["lock"],
                    fields=tuple(entry.get("fields", [])),
                    write_guarded=tuple(entry.get("write_guarded", [])),
                    where=tuple(entry.get("where", [])),
                    why=entry.get("why", ""),
                )
            )
        self._by_attr: Dict[str, List[LockDecl]] = {}
        for decl in self.locks.values():
            self._by_attr.setdefault(decl.attr, []).append(decl)
        self._guards_by_class: Dict[str, GuardDecl] = {
            g.cls: g for g in self.guards
        }

    @classmethod
    def load(cls, path: str = DEFAULT_MANIFEST_PATH) -> "LockManifest":
        return cls(_load_toml(path), path=path)

    # -- resolution --------------------------------------------------------

    def resolve(
        self,
        attr: str,
        class_name: Optional[str],
        path: str,
    ) -> Optional[LockDecl]:
        """The declared lock an acquisition site refers to, given the
        attribute spelling, the (inferred) owning class, and the file.
        Precedence: class match > path-prefix match > globally unique
        attribute. None means undeclared."""
        cands = self._by_attr.get(attr, [])
        if not cands:
            return None
        if class_name:
            by_cls = [d for d in cands if class_name in d.classes]
            if len(by_cls) == 1:
                return by_cls[0]
        norm = path.replace(os.sep, "/")
        by_path = [
            d for d in cands
            if any(w and w in norm for w in d.where)
        ]
        if len(by_path) == 1:
            return by_path[0]
        if len(by_path) > 1:  # longest prefix wins
            by_path.sort(key=lambda d: -max(len(w) for w in d.where if w in norm))
            return by_path[0]
        if len(cands) == 1:
            return cands[0]
        return None

    def guard_for(
        self, class_name: str, field: str
    ) -> Optional[Tuple[GuardDecl, str]]:
        """The guard declaration covering ``class_name.field`` and its
        mode (``"full"`` — reads and writes need the lock — or
        ``"write"`` — writes only). None when the field is unguarded."""
        g = self._guards_by_class.get(class_name)
        if g is None:
            return None
        if field in g.fields:
            return (g, "full")
        if field in g.write_guarded:
            return (g, "write")
        return None

    def guarded_class(self, class_name: str) -> Optional[GuardDecl]:
        return self._guards_by_class.get(class_name)

    def in_scanned_scope(self, path: str) -> bool:
        norm = path.replace(os.sep, "/")
        return any(prefix in norm for prefix in self.scan)

    def permits(self, held: str, acquired: str) -> bool:
        """Whether acquiring ``acquired`` while holding ``held`` is a
        declared edge (re-acquiring the same lock is reentrancy, always
        permitted — the RLocks handle it)."""
        return held == acquired or (held, acquired) in self.edges

    def allows_blocking(
        self, lock: str, chain: Sequence[str], primitive: str
    ) -> bool:
        """Whether a blocking call under ``lock`` is excused: some
        function along the call chain (or the primitive itself) matches
        an ``[[allow_blocking]]`` callee for this lock. Matching is by
        dotted-suffix, so ``callee = "WriteAheadLog.append"`` covers
        every path through the WAL's durable append."""
        for al_lock, callee, _why in self.allow_blocking:
            if al_lock != lock:
                continue
            for qual in list(chain) + [primitive]:
                if qual == callee or qual.endswith("." + callee):
                    return True
        return False

    def declared_cycles(self) -> List[List[str]]:
        """Cycles in the *declared* edge set — a manifest that permits a
        cycle is itself a deadlock license and gets reported."""
        graph: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, []).append(b)
        cycles: List[List[str]] = []
        state: Dict[str, int] = {}
        stack: List[str] = []

        def visit(node: str) -> None:
            state[node] = 1
            stack.append(node)
            for nxt in graph.get(node, []):
                if state.get(nxt, 0) == 1:
                    cycles.append(stack[stack.index(nxt):] + [nxt])
                elif state.get(nxt, 0) == 0:
                    visit(nxt)
            stack.pop()
            state[node] = 2

        for node in list(graph):
            if state.get(node, 0) == 0:
                visit(node)
        return cycles


_cached: Dict[str, LockManifest] = {}


def load_manifest(path: str = DEFAULT_MANIFEST_PATH) -> Optional[LockManifest]:
    """Load-and-cache; None when the manifest file is absent (the rules
    then stay silent rather than guessing)."""
    key = os.path.abspath(path)
    if key not in _cached:
        try:
            _cached[key] = LockManifest.load(path)
        except (OSError, KeyError, TypeError, ValueError):
            return None
    return _cached[key]
