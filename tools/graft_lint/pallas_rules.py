"""Pallas VMEM resource-model checkers.

Parses each kernel module's ``pl.BlockSpec``/``pltpu.VMEM``
declarations out of the AST, evaluates their shape expressions under
registered representative bindings (:data:`KERNEL_SHAPE_BINDINGS`), and
checks the resulting residency against the TPU's lane/sublane layout
and the ~16 MiB VMEM budget of
:mod:`raft_tpu.ops.pallas.vmem_model`. Rules:

* ``tile-align``     — a tile whose lane (last) dim is not a multiple
  of 128 or whose sublane (second-minor) dim is not a multiple of the
  dtype's sublane count gets physically padded; flagged when the
  padding wastes more than 256 KiB of VMEM per buffer.
* ``vmem-budget``    — the summed residency of all tiles (double-
  buffered when their index map varies along the inner grid axis) and
  scratch exceeds ``VMEM_HEADROOM x VMEM_LIMIT_BYTES``.
* ``stale-budget``   — a module-level hard-coded ``*_BUDGET`` integer
  that disagrees (>25%) with the budget derived from the same module's
  declarations, i.e. a calibrated constant that drifted from the
  shapes it was calibrated against (the failure mode that motivated
  graft-lint: ``pq_scan._DECODE_CHUNK_BUDGET``).
* ``vmem-unmodeled`` — a ``pallas_call`` module whose shape
  expressions cannot be resolved and which has no entry in
  :data:`KERNEL_SHAPE_BINDINGS`: the kernel runs outside the resource
  model's sight.

The AST model intentionally assumes 4 B/element for tiles whose dtype
it cannot see (BlockSpecs carry no dtype) — a conservative
overestimate for the bf16/u8 tiles. The byte-accurate accounting,
including kernel-body intermediates, lives in
``raft_tpu.ops.pallas.vmem_model`` and is asserted against the kernels
in tests; these checkers are the coarse always-on guardrail.
"""
from __future__ import annotations

import ast
import dataclasses
import math
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from raft_tpu.ops.pallas.vmem_model import VMEM_HEADROOM, VMEM_LIMIT_BYTES
from tools.graft_lint.core import Checker, LintModule, Violation

#: Representative shape bindings per kernel module (file stem). These
#: are the shapes the kernels are calibrated/benched at (the 1M-row
#: bench config); the checkers evaluate BlockSpec/scratch shape
#: expressions under them. A new kernel module must either use literal
#: shapes or register its bindings here — otherwise ``vmem-unmodeled``
#: fires.
KERNEL_SHAPE_BINDINGS: Dict[str, Dict[str, object]] = {
    "pq_scan": dict(
        qt=128, k=10, K=8192, rot_dim=128, g_lists=8, m=1152, gm=9216,
        bpr=32, banks=8,
    ),
    # the fused RaBitQ sign-bit kernel at the same 1M-row bench shape
    # (bpr = rot_dim/8 = 16 B/row of packed sign codes)
    "rabitq_scan": dict(
        qt=128, k=10, rot_dim=128, g_lists=8, m=1152, gm=9216, bpr=16,
        banks=8,
    ),
    "ivf_scan": dict(qt=128, k=10, d=128, m=1152, w=1024),
    # the fused CAGRA beam kernel at the 1M-row bench shape
    # (vmem_model.cagra_search_residency defaults)
    "cagra_search": dict(qt=32, itopk=160, width=8, deg=16, d=128),
    # the ICI ring top-k exchange at the 8-chip serving shape
    # (vmem_model.ring_topk_residency: n devices, B block rows, w = k;
    # kc = the scan-fused variant's candidate-tile width — 2k is the
    # widest that fits the 75% VMEM plan, see scan_ring_topk_residency)
    "ring_topk": dict(n=8, B=128, w=128, qt=32, kc=256),
    # tools/micro_layout.py — the layout microbench kernel
    "micro_layout": dict(QT=128, D=128, M=8704, block=(1, 8704, 128)),
}

#: Padding waste (bytes, per buffer) below which a misaligned tile is
#: tolerated — k-sized top-k accumulators pad to a lane but cost a few
#: tens of KiB, which is not worth contorting the API over.
ALIGN_WASTE_THRESHOLD = 256 * 1024

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
}

_BUDGET_NAME_RE = re.compile(r"BUDGET", re.IGNORECASE)

_EVAL_GLOBALS = {"__builtins__": {}, "min": min, "max": max, "len": len,
                 "int": int, "sum": sum, "abs": abs}


def _sublanes_for(itemsize: int) -> int:
    """Minimum sublane count of one physical tile: (8, 128) for 4-byte
    dtypes, (16, 128) for 2-byte, (32, 128) for 1-byte."""
    return max(8, 32 // max(itemsize, 1))


@dataclasses.dataclass
class SpecInfo:
    """One parsed BlockSpec/VMEM declaration."""

    node: ast.Call
    kind: str                    # "block" | "scratch"
    shape: Optional[Tuple[int, ...]]
    itemsize: int
    dtype_known: bool
    buffers: int                 # 2 when the index map tracks the inner grid axis
    unresolved: Optional[str] = None  # NameError detail when shape is None

    @property
    def nbytes(self) -> int:
        if not self.shape:
            return 0
        return int(math.prod(self.shape)) * self.itemsize * self.buffers

    def padded_nbytes(self) -> int:
        if not self.shape:
            return 0
        dims = list(self.shape)
        lane = dims[-1] if dims else 1
        sub = dims[-2] if len(dims) >= 2 else 1
        lead = int(math.prod(dims[:-2])) if len(dims) > 2 else 1
        sublanes = _sublanes_for(self.itemsize)
        plane = math.ceil(lane / 128) * 128
        # size-1 second-minor dims broadcast into one sublane group
        psub = sub if sub == 1 else math.ceil(sub / sublanes) * sublanes
        return lead * psub * plane * self.itemsize * self.buffers


class _PallasAliases(ast.NodeVisitor):
    def __init__(self) -> None:
        self.pl: Set[str] = set()
        self.pltpu: Set[str] = set()
        self.has_pallas_call = False

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for a in node.names:
            bound = a.asname or a.name
            if node.module == "jax.experimental" and a.name == "pallas":
                self.pl.add(bound)
            elif node.module == "jax.experimental.pallas" and a.name == "tpu":
                self.pltpu.add(bound)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "jax.experimental.pallas":
                self.pl.add(a.asname or "pallas")
            elif a.name == "jax.experimental.pallas.tpu":
                self.pltpu.add(a.asname or "tpu")


def _aliases(module: LintModule) -> _PallasAliases:
    cached = getattr(module, "_graft_pallas", None)
    if cached is None:
        cached = _PallasAliases()
        cached.visit(module.tree)
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "pallas_call"
            ):
                cached.has_pallas_call = True
                break
        module._graft_pallas = cached  # type: ignore[attr-defined]
    return cached


def _rooted_attr(node: ast.AST, roots: Set[str], attr: str) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id in roots
    )


def _eval_shape(
    node: ast.AST, bindings: Dict[str, int]
) -> Tuple[Optional[Tuple[int, ...]], Optional[str]]:
    """Evaluate a shape expression under restricted bindings. Returns
    (shape, unresolved-name) — exactly one is non-None."""
    try:
        code = compile(ast.Expression(body=node), "<graft-lint-shape>", "eval")
        val = eval(code, _EVAL_GLOBALS, dict(bindings))  # noqa: S307 — restricted
    except NameError as e:
        return None, str(e)
    except Exception as e:  # noqa: BLE001 — any non-shape expr: unresolved
        return None, f"{type(e).__name__}: {e}"
    if isinstance(val, int):
        val = (val,)
    if not (
        isinstance(val, tuple)
        and val
        and all(isinstance(d, int) and d > 0 for d in val)
    ):
        return None, f"not a positive int tuple: {val!r}"
    return tuple(val), None


def _lambda_tracks_inner_grid(node: ast.AST) -> bool:
    """True when an index_map lambda reads its second positional
    parameter (the inner grid axis) — Mosaic double-buffers that
    tile's DMA."""
    if not isinstance(node, ast.Lambda):
        return True  # unknown callable: assume the conservative 2x
    params = [p.arg for p in node.args.posonlyargs + node.args.args]
    if len(params) < 2:
        return False
    inner = params[1]
    return any(
        isinstance(n, ast.Name) and n.id == inner for n in ast.walk(node.body)
    )


def _dtype_itemsize(node: Optional[ast.AST]) -> Tuple[int, bool]:
    """(itemsize, known) from a dtype expression like ``jnp.float32``."""
    if isinstance(node, ast.Attribute) and node.attr in _DTYPE_BYTES:
        return _DTYPE_BYTES[node.attr], True
    if (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value in _DTYPE_BYTES
    ):
        return _DTYPE_BYTES[node.value], True
    return 4, False


def collect_specs(module: LintModule) -> List[SpecInfo]:
    """All BlockSpec / pltpu.VMEM declarations with evaluated shapes."""
    al = _aliases(module)
    if not (al.pl or al.pltpu):
        return []
    stem = os.path.splitext(os.path.basename(module.path))[0]
    bindings = KERNEL_SHAPE_BINDINGS.get(stem, {})
    out: List[SpecInfo] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if _rooted_attr(node.func, al.pl, "BlockSpec"):
            shape_node = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "block_shape":
                    shape_node = kw.value
            if shape_node is None or isinstance(shape_node, ast.Constant):
                continue  # memory-space-only spec
            index_map = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "index_map":
                    index_map = kw.value
            shape, unresolved = _eval_shape(shape_node, bindings)
            out.append(
                SpecInfo(
                    node=node, kind="block", shape=shape, itemsize=4,
                    dtype_known=False,
                    buffers=2 if index_map is not None and
                    _lambda_tracks_inner_grid(index_map) else 1,
                    unresolved=unresolved,
                )
            )
        elif _rooted_attr(node.func, al.pltpu, "VMEM"):
            if not node.args:
                continue
            shape, unresolved = _eval_shape(node.args[0], bindings)
            itemsize, known = _dtype_itemsize(
                node.args[1] if len(node.args) > 1 else None
            )
            out.append(
                SpecInfo(
                    node=node, kind="scratch", shape=shape, itemsize=itemsize,
                    dtype_known=known, buffers=1, unresolved=unresolved,
                )
            )
    return out


class TileAlignChecker(Checker):
    rule = "tile-align"
    doc = (
        "tile shape misaligned with the TPU (sublane x 128-lane) layout, "
        "wasting >256 KiB of padded VMEM per buffer."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for spec in collect_specs(module):
            if spec.shape is None:
                continue
            waste = spec.padded_nbytes() - spec.nbytes
            if waste > ALIGN_WASTE_THRESHOLD * spec.buffers:
                lane = spec.shape[-1]
                sub = spec.shape[-2] if len(spec.shape) >= 2 else 1
                hint = (
                    f"lane dim {lane} is not a multiple of 128"
                    if lane % 128
                    else f"sublane dim {sub} is not a multiple of "
                    f"{_sublanes_for(spec.itemsize)}"
                )
                yield self.violation(
                    module, spec.node,
                    f"{spec.kind} tile {'x'.join(map(str, spec.shape))} pads "
                    f"to the ({_sublanes_for(spec.itemsize)}, 128) layout "
                    f"wasting {waste // 1024} KiB of VMEM ({hint})"
                    + ("" if spec.dtype_known else "; assuming 4 B/elem"),
                )


class VmemBudgetChecker(Checker):
    rule = "vmem-budget"
    doc = (
        "summed tile+scratch residency (double-buffered along the inner "
        "grid axis) exceeds the headroom-adjusted ~16 MiB VMEM limit."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        specs = collect_specs(module)
        if not specs:
            return
        total = sum(s.nbytes for s in specs)
        limit = int(VMEM_LIMIT_BYTES * VMEM_HEADROOM)
        if total > limit:
            al = _aliases(module)
            anchor = specs[0].node
            yield self.violation(
                module, anchor,
                f"modeled tile+scratch residency {total} B "
                f"({total / 2**20:.2f} MiB) exceeds the "
                f"{VMEM_HEADROOM:.0%} x 16 MiB budget ({limit} B) at the "
                "registered calibration shapes — shrink a block or chunk "
                "the kernel"
                + ("" if al.has_pallas_call else " (no pallas_call found)"),
            )


class StaleBudgetChecker(Checker):
    rule = "stale-budget"
    doc = (
        "hard-coded *_BUDGET byte constant disagrees >25% with the "
        "budget derived from the module's own tile/scratch declarations "
        "— derive it (see raft_tpu.ops.pallas.vmem_model) instead."
    )

    TOLERANCE = 0.25

    def check(self, module: LintModule) -> Iterator[Violation]:
        specs = collect_specs(module)
        if not any(s.shape for s in specs):
            return
        fixed = sum(s.nbytes for s in specs)
        derived = int(VMEM_LIMIT_BYTES * VMEM_HEADROOM) - fixed
        if derived <= 0:
            return  # vmem-budget already covers this
        for node in module.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _BUDGET_NAME_RE.search(node.targets[0].id)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
            ):
                continue
            hard = node.value.value
            if abs(hard - derived) / derived > self.TOLERANCE:
                yield self.violation(
                    module, node,
                    f"hard-coded {node.targets[0].id} = {hard} disagrees "
                    f"with the derived VMEM budget {derived} (limit x "
                    f"{VMEM_HEADROOM:.0%} minus {fixed} B of modeled "
                    "residents) — derive it from the resource model so "
                    "shape drift moves the cap instead of breaking the "
                    "compile",
                )


class VmemUnmodeledChecker(Checker):
    rule = "vmem-unmodeled"
    doc = (
        "pallas_call module whose tile shapes cannot be resolved and "
        "which has no entry in KERNEL_SHAPE_BINDINGS — the kernel runs "
        "outside the VMEM resource model."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        al = _aliases(module)
        if not al.has_pallas_call:
            return
        specs = collect_specs(module)
        unresolved = [s for s in specs if s.shape is None]
        if not unresolved:
            return
        stem = os.path.splitext(os.path.basename(module.path))[0]
        registered = stem in KERNEL_SHAPE_BINDINGS
        s = unresolved[0]
        yield self.violation(
            module, s.node,
            f"{len(unresolved)} tile shape(s) could not be resolved "
            f"({s.unresolved}) — "
            + (
                "extend the module's entry in "
                if registered
                else "register representative shapes in "
            )
            + "tools/graft_lint/pallas_rules.py:KERNEL_SHAPE_BINDINGS so "
            "the VMEM model covers this kernel",
        )


CHECKERS = [
    TileAlignChecker(),
    VmemBudgetChecker(),
    StaleBudgetChecker(),
    VmemUnmodeledChecker(),
]
