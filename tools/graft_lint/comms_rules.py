"""Distributed-communication checkers.

The sharded search paths earned an O(k)-per-hop ring exchange
(:mod:`raft_tpu.ops.pallas.ring_topk`); the anti-pattern it replaces is
easy to reintroduce:

* ``gather-merge`` — a function that ``all_gather`` s two or more
  per-shard candidate arrays (the val/idx pair) and then runs a
  top-k/sort/merge over the concatenation. Every rank receives
  ``(n-1) x payload`` bytes and materialises the full
  ``n_shards x k`` candidate set just to throw most of it away — the
  communication-avoiding form is ``ring_topk`` (bit-identical ids) —
  or ``scan_ring_topk`` when the scan's wide candidate tile is still
  in hand (``merge_mode="fused_ring"``: the local fold happens inside
  the ring engine). The intentional gather sites — the parity
  reference engine and the fallback target for BOTH ring engines
  (``ring`` and ``fused_ring`` demote to the same gather on kernel
  failure, so the suppressed site backs two production paths) — carry
  a rationale'd ``# graft-lint: ignore[gather-merge]``.

* ``collective-divergence`` — a collective (``psum``/``ppermute``/
  ``all_gather``/…) issued under a branch that depends on the rank
  (``axis_index``/``process_index``), or a rank-dependent branch whose
  two arms issue *different* collective sequences, or a rank-dependent
  early exit with collectives after it. Collectives are rendezvous
  points: every rank in the axis must reach the same sequence or the
  pod hangs — and nothing catches it on one device, where rank 0 is
  the only rank and every branch agrees with itself. (Branching on a
  *traced* value fails loudly at trace time —
  ``ConcretizationTypeError`` — so the silent killer this rule hunts
  is specifically the rank-dependent Python branch, which traces
  fine.) Rank-dependent *data* is fine: ``jnp.where(rank == root, …)``
  masks values uniformly on every rank; it is rank-dependent *control
  flow* around a collective that diverges. Collectives reached through
  calls count too, via the project call graph.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from tools.graft_lint.core import (
    COLLECTIVE_PRIMITIVES,
    Checker,
    LintModule,
    Violation,
    walk_executed,
)

#: call names that consume a gathered candidate set as a merge/top-k
_MERGE_CALLS = frozenset(
    {"top_k", "approx_max_k", "approx_min_k", "merge_parts", "select_k",
     "sort", "argsort"}
)


def _attr_name(node: ast.Call) -> str:
    """Trailing name of ``f(...)`` / ``a.b.f(...)`` — matching on the
    last attribute keeps the check alias-robust (``lax.all_gather`` and
    ``jax.lax.all_gather`` both end in ``all_gather``)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


class GatherMergeChecker(Checker):
    rule = "gather-merge"
    doc = (
        "all_gather of per-shard candidate val/idx pairs followed by a "
        "top-k/sort merge — O(n_shards·k) wire and memory per rank; use "
        "ring_topk / scan_ring_topk (bit-identical ids, O(k) per hop) or "
        "suppress the intentional gather fallback — the reference engine "
        "both ring modes demote to — with a rationale"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            gathers = []
            merges = 0
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = _attr_name(sub)
                if name in ("all_gather", "allgather"):
                    gathers.append(sub)
                elif name in _MERGE_CALLS:
                    merges += 1
            # one gather is a verb/bcast implementation detail (comms.py's
            # own wrappers); the candidate-exchange smell needs the
            # val/idx PAIR gathered and then merged
            if len(gathers) >= 2 and merges:
                yield self.violation(
                    module, gathers[0],
                    f"{node.name} all_gathers {len(gathers)} per-shard "
                    "arrays and merges the concatenation — every rank "
                    "pays O(n_shards·k) wire/memory; use "
                    "ops.pallas.ring_topk.ring_topk (bit-identical ids) "
                    "or add a rationale'd suppression on the intentional "
                    "gather fallback",
                )


#: calls whose result identifies "which rank am I" — the taint seeds.
#: Deliberately NOT axis-size (`psum(1)`, `axis_size`): `if n == 1:`
#: shape-specialization branches are uniform across the axis.
_RANK_SOURCES = frozenset({"axis_index", "process_index", "comm_rank"})


def _is_rank_source(node: ast.expr) -> bool:
    return isinstance(node, ast.Call) and _attr_name(node) in _RANK_SOURCES


class CollectiveDivergenceChecker(Checker):
    rule = "collective-divergence"
    doc = (
        "collective op under a rank-dependent branch, or rank-dependent "
        "branch arms issuing different collective sequences — ranks "
        "stop agreeing on the rendezvous order and the pod hangs; "
        "passes every 1-device test"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        project = getattr(module, "project", None)
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            info = project.function_at(module, fn) if project is not None else None
            tainted = self._taint_set(fn)
            yield from self._scan_block(module, project, info, fn.body, tainted)

    # -- taint --------------------------------------------------------------

    def _taint_set(self, fn) -> Set[str]:
        """Names in ``fn`` holding rank-derived values: seeded by
        ``axis_index()``-family calls, closed over simple assignments
        (``is_root = rank == 0`` taints ``is_root``)."""
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in walk_executed(fn.body):
                if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    continue
                value = node.value
                if value is None or not self._expr_tainted(value, tainted):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for tgt in targets:
                    for leaf in ast.walk(tgt):
                        if isinstance(leaf, ast.Name) and leaf.id not in tainted:
                            tainted.add(leaf.id)
                            changed = True
        return tainted

    def _expr_tainted(self, expr: ast.expr, tainted: Set[str]) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
            if _is_rank_source(sub):
                return True
        return False

    # -- footprints ---------------------------------------------------------

    def _footprint(self, project, info, stmts) -> Tuple[str, ...]:
        """Sorted collective sequence a statement list may issue: direct
        calls with multiplicity plus (transitively, via the call graph)
        collectives of resolved callees."""
        out = []
        trans: Set[str] = set()
        for node in walk_executed(stmts):
            if not isinstance(node, ast.Call):
                continue
            name = _attr_name(node)
            if name in COLLECTIVE_PRIMITIVES:
                out.append(name)
            elif project is not None and info is not None:
                target = project.resolve_call(info, node)
                if target is not None:
                    trans.update(project.collective_facts().get(target, {}))
        return tuple(sorted(out) + sorted(trans - set(out)))

    @staticmethod
    def _exits(stmts) -> bool:
        return any(
            isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
            for s in stmts
        )

    # -- scan ---------------------------------------------------------------

    def _scan_block(self, module, project, info, stmts, tainted):
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, ast.If) and self._expr_tainted(stmt.test, tainted):
                body_fp = self._footprint(project, info, stmt.body)
                else_fp = self._footprint(project, info, stmt.orelse)
                if body_fp != else_fp:
                    diff = sorted(set(body_fp) ^ set(else_fp)) or sorted(set(body_fp))
                    yield self.violation(
                        module, stmt,
                        "branch on a rank-dependent value issues different "
                        f"collective sequences per arm ({', '.join(diff)}) "
                        "— ranks taking different arms stop agreeing on "
                        "the rendezvous order and the pod hangs; issue the "
                        "same collectives on every rank and select results "
                        "with jnp.where(rank == ..., ...)",
                    )
                elif (
                    self._exits(stmt.body) != self._exits(stmt.orelse)
                    and self._footprint(project, info, stmts[i + 1:])
                ):
                    yield self.violation(
                        module, stmt,
                        "rank-dependent early exit skips the collectives "
                        "issued after this branch on some ranks — the "
                        "remaining ranks block forever at the rendezvous; "
                        "every rank must run the same collective sequence",
                    )
            elif isinstance(stmt, ast.While) and self._expr_tainted(stmt.test, tainted):
                fp = self._footprint(project, info, stmt.body)
                if fp:
                    yield self.violation(
                        module, stmt,
                        "while-loop with a rank-dependent condition issues "
                        f"collectives ({', '.join(sorted(set(fp)))}) — "
                        "ranks run different trip counts and desynchronize "
                        "at the rendezvous; hoist the collective or make "
                        "the trip count uniform",
                    )
            elif isinstance(stmt, ast.For) and self._expr_tainted(stmt.iter, tainted):
                fp = self._footprint(project, info, stmt.body)
                if fp:
                    yield self.violation(
                        module, stmt,
                        "for-loop over a rank-dependent range issues "
                        f"collectives ({', '.join(sorted(set(fp)))}) — "
                        "trip counts differ per rank and the pod hangs at "
                        "the first unmatched rendezvous; loop bounds must "
                        "be uniform across the axis",
                    )
            # recurse into nested statement bodies (skip nested defs —
            # they are checked as their own functions)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub and not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield from self._scan_block(module, project, info, sub, tainted)
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._scan_block(
                    module, project, info, handler.body, tainted
                )


CHECKERS = [GatherMergeChecker(), CollectiveDivergenceChecker()]
