"""Distributed-communication checkers.

The sharded search paths earned an O(k)-per-hop ring exchange
(:mod:`raft_tpu.ops.pallas.ring_topk`); the anti-pattern it replaces is
easy to reintroduce:

* ``gather-merge`` — a function that ``all_gather`` s two or more
  per-shard candidate arrays (the val/idx pair) and then runs a
  top-k/sort/merge over the concatenation. Every rank receives
  ``(n-1) x payload`` bytes and materialises the full
  ``n_shards x k`` candidate set just to throw most of it away — the
  communication-avoiding form is ``ring_topk`` (bit-identical ids).
  The intentional gather sites — the parity reference engine and the
  ring's fallback target — carry a rationale'd
  ``# graft-lint: ignore[gather-merge]``.
"""
from __future__ import annotations

import ast
from typing import Iterator

from tools.graft_lint.core import Checker, LintModule, Violation

#: call names that consume a gathered candidate set as a merge/top-k
_MERGE_CALLS = frozenset(
    {"top_k", "approx_max_k", "approx_min_k", "merge_parts", "select_k",
     "sort", "argsort"}
)


def _attr_name(node: ast.Call) -> str:
    """Trailing name of ``f(...)`` / ``a.b.f(...)`` — matching on the
    last attribute keeps the check alias-robust (``lax.all_gather`` and
    ``jax.lax.all_gather`` both end in ``all_gather``)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


class GatherMergeChecker(Checker):
    rule = "gather-merge"
    doc = (
        "all_gather of per-shard candidate val/idx pairs followed by a "
        "top-k/sort merge — O(n_shards·k) wire and memory per rank; use "
        "ring_topk (bit-identical ids, O(k) per hop) or suppress the "
        "intentional gather fallback with a rationale"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            gathers = []
            merges = 0
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = _attr_name(sub)
                if name in ("all_gather", "allgather"):
                    gathers.append(sub)
                elif name in _MERGE_CALLS:
                    merges += 1
            # one gather is a verb/bcast implementation detail (comms.py's
            # own wrappers); the candidate-exchange smell needs the
            # val/idx PAIR gathered and then merged
            if len(gathers) >= 2 and merges:
                yield self.violation(
                    module, gathers[0],
                    f"{node.name} all_gathers {len(gathers)} per-shard "
                    "arrays and merges the concatenation — every rank "
                    "pays O(n_shards·k) wire/memory; use "
                    "ops.pallas.ring_topk.ring_topk (bit-identical ids) "
                    "or add a rationale'd suppression on the intentional "
                    "gather fallback",
                )


CHECKERS = [GatherMergeChecker()]
