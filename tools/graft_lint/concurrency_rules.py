"""Concurrency checkers: the lock-order rule.

The serving stack synchronizes through a handful of locks whose
ordering contract is declared in ``tools/graft_lint/lock_order.toml``
(see :mod:`tools.graft_lint.lockmanifest`). This module derives the
*actual* acquisition-edge set — lock held → lock acquired, looking
through calls via the project call graph — and reports:

* ``lock-order`` / undeclared lock: a lock-like ``with`` inside the
  scanned packages that no ``[[lock]]`` declaration matches. An
  undeclared lock is exactly how ``Compactor._state_lock`` drifted out
  of the documented ordering — declare it, with its position.
* ``lock-order`` / inversion: an observed edge whose *reverse* is
  declared. Two threads taking the two orders deadlock; this is the
  classic AB/BA.
* ``lock-order`` / undeclared edge: an observed edge the manifest does
  not permit. Either the code is wrong or the contract is incomplete —
  both need a human: declare the edge with a rationale or reorder the
  code.
* ``lock-order`` / manifest cycle: the declared edge set itself
  contains a cycle — the manifest licenses a deadlock.

Edges are derived both from lexically nested ``with`` blocks and from
calls made while a lock is held whose callees (transitively) acquire
locks. Calls the graph cannot resolve contribute nothing — an unknown
callee degrades coverage, never correctness of what *is* reported. The
runtime witness (:mod:`raft_tpu.utils.lockcheck`) closes that gap
dynamically under the chaos suites.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from tools.graft_lint import lockmanifest
from tools.graft_lint.core import (
    Checker,
    FunctionInfo,
    LintModule,
    LintProject,
    Violation,
    walk_executed,
)

#: substrings of a ``with`` context-expression name that mark it as a
#: lock acquisition (kept in sync with robust_rules._LOCK_HINTS)
_LOCK_HINTS = ("lock", "mutex")


def _context_attr(expr: ast.expr) -> Optional[str]:
    """Rightmost name of a with-context expression (``mut._lock`` ->
    "_lock"), unwrapping a call (``lock.acquire()`` shapes)."""
    while isinstance(expr, ast.Call):
        expr = expr.func
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_lock_like(expr: ast.expr) -> bool:
    name = _context_attr(expr)
    return name is not None and any(h in name.lower() for h in _LOCK_HINTS)


def resolve_lock(
    project: Optional[LintProject],
    manifest: "lockmanifest.LockManifest",
    module: LintModule,
    info: Optional[FunctionInfo],
    expr: ast.expr,
):
    """The manifest :class:`~tools.graft_lint.lockmanifest.LockDecl` a
    with-context expression acquires, or None. Class context comes from
    ``self`` or from the receiver's inferred type (``mut: MutableIndex``
    → class MutableIndex)."""
    attr = _context_attr(expr)
    if attr is None:
        return None
    class_name = None
    base = expr
    while isinstance(base, ast.Call):
        base = base.func
    if isinstance(base, ast.Attribute) and project is not None and info is not None:
        recv = project.infer_type(info, base.value)
        if recv is not None:
            class_name = recv.rsplit(".", 1)[-1]
    return manifest.resolve(attr, class_name, module.path)


def acquired_lock_facts(
    project: LintProject, manifest: "lockmanifest.LockManifest"
) -> Dict[str, Dict]:
    """function qual -> {canonical lock name: (line, call_path)} —
    which declared locks a function may acquire, directly or through
    calls. Cached on the project."""
    key = ("locks", manifest.path)
    if key not in project._fact_cache:
        def direct(info: FunctionInfo):
            out = {}
            for node in walk_executed(info.node.body):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        decl = resolve_lock(
                            project, manifest, info.module, info, item.context_expr
                        )
                        if decl is not None and decl.name not in out:
                            out[decl.name] = node.lineno
            return out
        project._fact_cache[key] = project.propagate(direct)
    return project._fact_cache[key]


class LockOrderChecker(Checker):
    rule = "lock-order"
    doc = (
        "lock acquisition (direct or through calls) that inverts or "
        "escapes the declared ordering manifest lock_order.toml, or a "
        "lock the manifest does not know — potential deadlock or "
        "contract drift"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        manifest = lockmanifest.load_manifest()
        if manifest is None:
            return
        project = module.project
        # manifest self-check: report declared cycles once per project
        if project is not None and not getattr(project, "_lock_cycles_done", False):
            project._lock_cycles_done = True
            for cyc in manifest.declared_cycles():
                yield Violation(
                    rule=self.rule, path=module.path, line=1, col=1,
                    message=(
                        "lock_order.toml declares a cyclic order "
                        f"({' -> '.join(cyc)}) — the manifest itself "
                        "licenses a deadlock; break the cycle"
                    ),
                )
        self._seen: set = set()
        handled: set = set()
        if project is not None:
            acquired = acquired_lock_facts(project, manifest)
            for info in project.functions.values():
                if info.module is not module:
                    continue
                for node in walk_executed(info.node.body):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        if id(node) in handled:
                            continue
                        yield from self._scan_with(
                            project, manifest, module, info, acquired,
                            node, [], handled,
                        )
        # module-level / nested-def withs the function index missed:
        # still check for undeclared locks (no receiver typing)
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)) and id(node) not in handled:
                handled.add(id(node))
                for item in node.items:
                    yield from self._check_item(
                        manifest, module, None, item, node, []
                    )

    def _check_item(self, manifest, module, decl, item, node, held):
        """Violations for one with-item given its resolved decl (or
        None): undeclared-lock and bad direct edges."""
        if decl is None:
            if _is_lock_like(item.context_expr) and manifest.in_scanned_scope(module.path):
                attr = _context_attr(item.context_expr)
                key = ("undeclared", node.lineno, attr)
                if key not in self._seen:
                    self._seen.add(key)
                    yield Violation(
                        rule=self.rule, path=module.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"'{attr}' looks like a lock but no [[lock]] in "
                            "lock_order.toml matches it — declare it (canonical "
                            "name, class, path) so its ordering is checkable"
                        ),
                    )
            return
        for h in held:
            yield from self._edge(manifest, module, node, h, decl.name, [])

    def _edge(self, manifest, module, node, held, acquired, chain):
        if manifest.permits(held, acquired):
            return
        key = ("edge", node.lineno, held, acquired)
        if key in self._seen:
            return
        self._seen.add(key)
        via = f" (via {' -> '.join(chain)})" if chain else ""
        if (acquired, held) in manifest.edges:
            yield Violation(
                rule=self.rule, path=module.path, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"acquiring {acquired} while holding {held} INVERTS the "
                    f"declared edge {acquired} -> {held}{via} — two threads "
                    "taking both orders deadlock; reorder to match "
                    "lock_order.toml"
                ),
            )
        else:
            yield Violation(
                rule=self.rule, path=module.path, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"acquisition edge {held} -> {acquired}{via} is not "
                    "permitted by lock_order.toml — declare it with a "
                    "rationale or restructure so the lock is not held here"
                ),
            )

    def _scan_with(
        self, project, manifest, module, info, acquired, node, held, handled
    ):
        """Recursive scan of a with-statement: check its items against
        the held set, then its body with the item locks added."""
        handled.add(id(node))
        new_held = list(held)
        for item in node.items:
            decl = resolve_lock(project, manifest, module, info, item.context_expr)
            yield from self._check_item(manifest, module, decl, item, node, new_held)
            if decl is not None:
                new_held.append(decl.name)
        yield from self._scan_body(
            project, manifest, module, info, acquired, node.body, new_held, handled
        )

    def _scan_body(
        self, project, manifest, module, info, acquired, stmts, held, handled
    ):
        stack = list(stmts)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                yield from self._scan_with(
                    project, manifest, module, info, acquired, node, held, handled
                )
                continue
            if isinstance(node, ast.Call) and held:
                target = project.resolve_call(info, node)
                if target is not None:
                    for lock_name, (_ln, path) in acquired.get(target, {}).items():
                        for h in held:
                            yield from self._edge(
                                manifest, module, node, h, lock_name,
                                [target] + path,
                            )
            stack.extend(ast.iter_child_nodes(node))


CHECKERS = [LockOrderChecker()]
