"""JAX tracing/correctness checkers.

These rules encode the tracing invariants that turn into runtime
``TracerBoolConversionError``s, silent host round-trips, or
recompilation storms — the failure modes the reference project catches
with compile-time template checks and we can only catch by reading the
AST:

* ``traced-branch``   — Python ``if``/``while`` on a traced value
  inside a ``@jax.jit`` function (trace-time crash or silent
  specialization).
* ``numpy-in-jit``    — ``np.*`` called on a traced value inside a
  jitted function (forces a host transfer / breaks tracing).
* ``static-args``     — ``static_argnames`` naming a parameter that
  does not exist, ``static_argnums`` out of range, or a static
  parameter with a non-hashable default.
* ``jit-in-loop``     — ``jax.jit`` (or ``partial(jax.jit, ...)``)
  constructed inside a loop: every iteration builds a fresh wrapper
  with an empty compilation cache.
* ``implicit-dtype``  — ``jnp.arange``/``jnp.linspace`` with float
  arguments and no explicit ``dtype``: the result dtype flips between
  f32 and f64 with the ``jax_enable_x64`` flag.
* ``unsynced-timing`` — a wall-clock delta (``time.perf_counter() -
  t0``) around a call to a module-local jitted function with no device
  sync inside the timed region: async dispatch means the delta measures
  enqueue, not compute.

The taint analysis is a deliberate approximation: a name is *traced* if
it is a non-static parameter of the jitted function or was assigned
from an expression that reads a traced name outside a static context
(``.shape``/``.ndim``/``.dtype``/``.size``, ``len()``, ``isinstance``,
``x is None``). No interprocedural propagation — helpers called from a
jitted function are each analyzed only if jitted themselves.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.graft_lint.core import Checker, LintModule, Violation

# attribute reads that yield trace-time constants even on tracers
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "weak_type"}
# calls whose result is a Python value at trace time (or that fail
# loudly on tracers anyway, which is not this rule's business)
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "int", "float", "bool", "str"}


class _Imports(ast.NodeVisitor):
    """Module-level import aliases for numpy / jax / jax.numpy /
    functools.partial / jax.jit."""

    def __init__(self) -> None:
        self.numpy: Set[str] = set()
        self.jax: Set[str] = set()
        self.jnp: Set[str] = set()
        self.jit: Set[str] = set()       # names bound directly to jax.jit
        self.partial: Set[str] = set()   # names bound to functools.partial
        self.functools: Set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = a.asname or a.name.split(".")[0]
            if a.name == "numpy":
                self.numpy.add(a.asname or "numpy")
            elif a.name == "jax":
                self.jax.add(a.asname or "jax")
            elif a.name == "jax.numpy":
                self.jnp.add(a.asname or name)
            elif a.name == "functools":
                self.functools.add(a.asname or "functools")

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for a in node.names:
            bound = a.asname or a.name
            if node.module == "jax" and a.name == "numpy":
                self.jnp.add(bound)
            elif node.module == "jax" and a.name == "jit":
                self.jit.add(bound)
            elif node.module == "functools" and a.name == "partial":
                self.partial.add(bound)
            elif node.module == "numpy":
                pass  # from numpy import X — too fine-grained to track


def _module_imports(module: LintModule) -> _Imports:
    cached = getattr(module, "_graft_imports", None)
    if cached is None:
        cached = _Imports()
        cached.visit(module.tree)
        module._graft_imports = cached  # type: ignore[attr-defined]
    return cached


def _is_jit_expr(node: ast.AST, imp: _Imports) -> bool:
    """``jax.jit`` / ``jit`` (imported from jax)."""
    if isinstance(node, ast.Name):
        return node.id in imp.jit
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id in imp.jax
    )


def _is_partial_expr(node: ast.AST, imp: _Imports) -> bool:
    if isinstance(node, ast.Name):
        return node.id in imp.partial
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "partial"
        and isinstance(node.value, ast.Name)
        and node.value.id in imp.functools
    )


def _jit_call_keywords(node: ast.AST, imp: _Imports) -> Optional[List[ast.keyword]]:
    """If ``node`` is a jit construction (``jax.jit``, ``jax.jit(...)``,
    ``partial(jax.jit, ...)``), return its keyword list (may be empty);
    else None."""
    if _is_jit_expr(node, imp):
        return []
    if isinstance(node, ast.Call):
        if _is_jit_expr(node.func, imp):
            return list(node.keywords)
        if (
            _is_partial_expr(node.func, imp)
            and node.args
            and _is_jit_expr(node.args[0], imp)
        ):
            return list(node.keywords)
    return None


def _const_str_seq(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return out
    return None


def _const_int_seq(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return out
    return None


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _static_params(fn: ast.FunctionDef, keywords: Sequence[ast.keyword]) -> Set[str]:
    """Parameter names declared static via static_argnames/argnums."""
    statics: Set[str] = set()
    pos = [p.arg for p in fn.args.posonlyargs + fn.args.args]
    for kw in keywords:
        if kw.arg == "static_argnames":
            statics.update(_const_str_seq(kw.value) or [])
        elif kw.arg == "static_argnums":
            for i in _const_int_seq(kw.value) or []:
                if -len(pos) <= i < len(pos):
                    statics.add(pos[i])
    return statics


def iter_jitted_functions(
    module: LintModule,
) -> Iterator[Tuple[ast.FunctionDef, List[ast.keyword], ast.AST]]:
    """(function def, jit keywords, decorator node) for every function
    whose decorator list contains a jit construction."""
    imp = _module_imports(module)
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            kws = _jit_call_keywords(deco, imp)
            if kws is not None:
                yield node, kws, deco
                break


def _is_none_check(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Compare)
        and len(node.ops) == 1
        and isinstance(node.ops[0], (ast.Is, ast.IsNot))
        and (
            (isinstance(node.comparators[0], ast.Constant) and node.comparators[0].value is None)
            or (isinstance(node.left, ast.Constant) and node.left.value is None)
        )
    )


def _tainted(node: Optional[ast.AST], traced: Set[str]) -> bool:
    """Does this expression read a traced name in a value position?"""
    if node is None:
        return False
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _tainted(node.value, traced)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id in _STATIC_CALLS:
            return False
        return any(
            _tainted(c, traced)
            for c in [node.func, *node.args, *[k.value for k in node.keywords]]
        )
    if _is_none_check(node):
        return False
    if isinstance(node, ast.Lambda):
        shadow = {p.arg for p in node.args.posonlyargs + node.args.args + node.args.kwonlyargs}
        return _tainted(node.body, traced - shadow)
    if isinstance(node, ast.Constant):
        return False
    return any(_tainted(c, traced) for c in ast.iter_child_nodes(node))


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        return [n for e in target.elts for n in _target_names(e)]
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []  # attribute / subscript targets: not name bindings


class _JitBodyEvents:
    """Events collected in one ordered pass over a jitted function."""

    def __init__(self) -> None:
        self.dynamic_tests: List[Tuple[str, ast.AST]] = []  # ("if"|"while", node)
        self.numpy_calls: List[ast.Call] = []


def _scan_exprs_for_numpy(
    exprs: Sequence[Optional[ast.AST]],
    traced: Set[str],
    imp: _Imports,
    events: _JitBodyEvents,
) -> None:
    for expr in exprs:
        if expr is None:
            continue
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id in imp.numpy
            ):
                continue
            args = [*node.args, *[k.value for k in node.keywords]]
            if any(_tainted(a, traced) for a in args):
                events.numpy_calls.append(node)


def _stmt_exprs(stmt: ast.stmt) -> List[Optional[ast.AST]]:
    """The expression fields owned by one statement (no child stmts)."""
    if isinstance(stmt, ast.Assign):
        return [stmt.value]
    if isinstance(stmt, ast.AugAssign):
        return [stmt.value]
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.value]
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, ast.Return):
        return [stmt.value]
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, ast.For):
        return [stmt.iter]
    if isinstance(stmt, ast.With):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Assert):
        return [stmt.test, stmt.msg]
    if isinstance(stmt, ast.Raise):
        return [stmt.exc, stmt.cause]
    return []


def _walk_jit_body(
    body: Sequence[ast.stmt],
    traced: Set[str],
    imp: _Imports,
    events: _JitBodyEvents,
) -> None:
    """Ordered walk: propagate taint through assignments, record
    dynamic ``if``/``while`` tests and numpy-on-traced calls."""
    for stmt in body:
        _scan_exprs_for_numpy(_stmt_exprs(stmt), traced, imp, events)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def (kernel closure, scan body): closure names keep
            # their taint, fresh params shadow as untraced
            shadow = traced - set(_param_names(stmt)) - {stmt.name}
            _walk_jit_body(stmt.body, shadow, imp, events)
            traced.discard(stmt.name)
        elif isinstance(stmt, ast.Assign):
            tainted = _tainted(stmt.value, traced)
            for t in stmt.targets:
                for name in _target_names(t):
                    (traced.add if tainted else traced.discard)(name)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None and _tainted(stmt.value, traced):
                for name in _target_names(stmt.target):
                    traced.add(name)
        elif isinstance(stmt, ast.If):
            if _tainted(stmt.test, traced):
                events.dynamic_tests.append(("if", stmt))
            _walk_jit_body(stmt.body, traced, imp, events)
            _walk_jit_body(stmt.orelse, traced, imp, events)
        elif isinstance(stmt, ast.While):
            if _tainted(stmt.test, traced):
                events.dynamic_tests.append(("while", stmt))
            _walk_jit_body(stmt.body, traced, imp, events)
            _walk_jit_body(stmt.orelse, traced, imp, events)
        elif isinstance(stmt, ast.For):
            if _tainted(stmt.iter, traced):
                for name in _target_names(stmt.target):
                    traced.add(name)
            _walk_jit_body(stmt.body, traced, imp, events)
            _walk_jit_body(stmt.orelse, traced, imp, events)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                if item.optional_vars is not None and _tainted(
                    item.context_expr, traced
                ):
                    for name in _target_names(item.optional_vars):
                        traced.add(name)
            _walk_jit_body(stmt.body, traced, imp, events)
        elif isinstance(stmt, ast.Try):
            _walk_jit_body(stmt.body, traced, imp, events)
            for h in stmt.handlers:
                _walk_jit_body(h.body, traced, imp, events)
            _walk_jit_body(stmt.orelse, traced, imp, events)
            _walk_jit_body(stmt.finalbody, traced, imp, events)


def _analyze_jitted(
    module: LintModule, fn: ast.FunctionDef, keywords: Sequence[ast.keyword]
) -> _JitBodyEvents:
    cache: Dict[int, _JitBodyEvents] = getattr(module, "_graft_jit_cache", None) or {}
    key = id(fn)
    if key not in cache:
        imp = _module_imports(module)
        statics = _static_params(fn, keywords)
        traced = set(_param_names(fn)) - statics
        events = _JitBodyEvents()
        _walk_jit_body(fn.body, traced, imp, events)
        cache[key] = events
        module._graft_jit_cache = cache  # type: ignore[attr-defined]
    return cache[key]


class TracedBranchChecker(Checker):
    rule = "traced-branch"
    doc = (
        "Python if/while on a traced value inside a @jax.jit function — "
        "use lax.cond/lax.while_loop/jnp.where, or declare the argument "
        "static."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for fn, kws, _ in iter_jitted_functions(module):
            events = _analyze_jitted(module, fn, kws)
            for kind, node in events.dynamic_tests:
                yield self.violation(
                    module, node,
                    f"Python `{kind}` tests a traced value inside jitted "
                    f"`{fn.name}` — this fails (or silently specializes) at "
                    "trace time; use lax.cond/lax.while_loop/jnp.where or "
                    "mark the argument static",
                )


class NumpyInJitChecker(Checker):
    rule = "numpy-in-jit"
    doc = (
        "np.* called on a traced value inside a @jax.jit function — "
        "forces a host transfer at trace time; use jnp.*."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for fn, kws, _ in iter_jitted_functions(module):
            events = _analyze_jitted(module, fn, kws)
            for call in events.numpy_calls:
                attr = call.func.attr if isinstance(call.func, ast.Attribute) else "?"
                yield self.violation(
                    module, call,
                    f"np.{attr}(...) receives a traced value inside jitted "
                    f"`{fn.name}` — numpy cannot trace; use the jnp "
                    "equivalent",
                )


class StaticArgsChecker(Checker):
    rule = "static-args"
    doc = (
        "static_argnames naming a missing parameter, static_argnums out "
        "of range, or a static parameter with a non-hashable default."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for fn, kws, deco in iter_jitted_functions(module):
            params = set(_param_names(fn))
            pos = [p.arg for p in fn.args.posonlyargs + fn.args.args]
            for kw in kws:
                if kw.arg == "static_argnames":
                    for name in _const_str_seq(kw.value) or []:
                        if name not in params:
                            yield self.violation(
                                module, deco,
                                f"static_argnames names `{name}` which is not "
                                f"a parameter of `{fn.name}` — jax raises at "
                                "first call",
                            )
                elif kw.arg == "static_argnums":
                    for i in _const_int_seq(kw.value) or []:
                        if not (-len(pos) <= i < len(pos)):
                            yield self.violation(
                                module, deco,
                                f"static_argnums index {i} is out of range for "
                                f"`{fn.name}` ({len(pos)} positional params)",
                            )
            # non-hashable defaults on static params leak into the jit
            # cache key and raise at call time
            statics = _static_params(fn, kws)
            defaults = fn.args.defaults
            pos_with_default = pos[len(pos) - len(defaults):] if defaults else []
            kw_pairs = zip(fn.args.kwonlyargs, fn.args.kw_defaults)
            pairs = list(zip(pos_with_default, defaults)) + [
                (p.arg, d) for p, d in kw_pairs if d is not None
            ]
            for name, default in pairs:
                if name in statics and isinstance(
                    default, (ast.List, ast.Dict, ast.Set)
                ):
                    yield self.violation(
                        module, default,
                        f"static parameter `{name}` of `{fn.name}` has a "
                        "non-hashable default — jit cache keys must be "
                        "hashable; use a tuple/frozenset",
                    )


class JitInLoopChecker(Checker):
    rule = "jit-in-loop"
    doc = (
        "jax.jit (or partial(jax.jit, ...)) constructed inside a loop — "
        "every iteration builds a fresh wrapper and recompiles; hoist "
        "the jitted function out of the loop."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        imp = _module_imports(module)
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if node is loop:
                    continue
                if isinstance(node, ast.Call) and _jit_call_keywords(node, imp) is not None:
                    yield self.violation(
                        module, node,
                        "jax.jit constructed inside a loop — each iteration "
                        "makes a fresh wrapper with an empty compile cache; "
                        "hoist it out of the loop",
                    )


def _has_float_arg(call: ast.Call) -> bool:
    for a in call.args:
        v = a
        if isinstance(v, ast.UnaryOp):
            v = v.operand
        if isinstance(v, ast.Constant) and isinstance(v.value, float):
            return True
    return False


class ImplicitDtypeChecker(Checker):
    rule = "implicit-dtype"
    doc = (
        "jnp.arange/linspace with float arguments and no explicit dtype "
        "— the result flips f32/f64 with the jax_enable_x64 flag."
    )

    _FNS = {"arange", "linspace", "geomspace", "logspace"}

    def check(self, module: LintModule) -> Iterator[Violation]:
        imp = _module_imports(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr in self._FNS
                and isinstance(f.value, ast.Name)
                and f.value.id in imp.jnp
            ):
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            # arange(start, stop, step, dtype) — 4th positional is dtype
            if f.attr == "arange" and len(node.args) >= 4:
                continue
            if _has_float_arg(node):
                yield self.violation(
                    module, node,
                    f"jnp.{f.attr} with float arguments and no dtype — the "
                    "result dtype depends on the jax_enable_x64 flag; pass "
                    "an explicit dtype",
                )


# -- unsynced-timing --------------------------------------------------------

#: clock functions on the ``time`` module whose subtraction forms a delta
_TIMER_FUNCS = {"time", "perf_counter", "monotonic"}
#: bare calls that force device completion (scalar fetch / host copy)
_SYNC_NAME_CALLS = {"float", "int", "bool"}
#: attribute calls that force device completion
_SYNC_ATTRS = {"block_until_ready", "device_get", "asarray", "item"}


class _TimeImports(ast.NodeVisitor):
    """Module-level aliases of the ``time`` module and its clocks."""

    def __init__(self) -> None:
        self.time_mod: Set[str] = set()
        self.clocks: Set[str] = set()  # from time import perf_counter [as pc]

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "time":
                self.time_mod.add(a.asname or "time")

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for a in node.names:
                if a.name in _TIMER_FUNCS:
                    self.clocks.add(a.asname or a.name)


def _time_imports(module: LintModule) -> _TimeImports:
    cached = getattr(module, "_graft_time_imports", None)
    if cached is None:
        cached = _TimeImports()
        cached.visit(module.tree)
        module._graft_time_imports = cached  # type: ignore[attr-defined]
    return cached


def _is_clock_call(node: ast.AST, timp: _TimeImports) -> bool:
    """``time.perf_counter()`` / ``perf_counter()`` (module-level alias)."""
    if not (isinstance(node, ast.Call) and not node.args and not node.keywords):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in timp.clocks
    return (
        isinstance(f, ast.Attribute)
        and f.attr in _TIMER_FUNCS
        and isinstance(f.value, ast.Name)
        and f.value.id in timp.time_mod
    )


def _jitted_names(module: LintModule) -> Set[str]:
    """Module-local names known to be jitted callables: jit-decorated
    defs plus ``name = jax.jit(...)`` / ``name = partial(jax.jit, ...)``
    assignments."""
    cached = getattr(module, "_graft_jitted_names", None)
    if cached is not None:
        return cached
    imp = _module_imports(module)
    names: Set[str] = {fn.name for fn, _, _ in iter_jitted_functions(module)}
    for node in ast.walk(module.tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and _jit_call_keywords(node.value, imp) is not None
        ):
            for t in node.targets:
                names.update(_target_names(t))
    module._graft_jitted_names = names  # type: ignore[attr-defined]
    return names


def _walk_skip_defs(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested function bodies
    (they run on their own clock, not inside this timed region)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _iter_bodies(tree: ast.AST) -> Iterator[Sequence[ast.stmt]]:
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            body = getattr(node, field, None)
            if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
                yield body


def _calls_jitted(stmts: Sequence[ast.stmt], jitted: Set[str]) -> bool:
    for stmt in stmts:
        for node in _walk_skip_defs(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted
            ):
                return True
    return False


def _has_sync(stmts: Sequence[ast.stmt]) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in _SYNC_NAME_CALLS:
                return True
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
                return True
    return False


class UnsyncedTimingChecker(Checker):
    rule = "unsynced-timing"
    doc = (
        "wall-clock delta around a call to a jitted function with no "
        "device sync in the timed region — async dispatch means the "
        "delta measures enqueue time, not compute; block_until_ready "
        "(or a scalar fetch) before reading the clock."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        timp = _time_imports(module)
        if not (timp.time_mod or timp.clocks):
            return
        jitted = _jitted_names(module)
        if not jitted:
            return
        for body in _iter_bodies(module.tree):
            yield from self._scan_body(module, body, timp, jitted)

    def _scan_body(
        self,
        module: LintModule,
        body: Sequence[ast.stmt],
        timp: _TimeImports,
        jitted: Set[str],
    ) -> Iterator[Violation]:
        starts: Dict[str, int] = {}  # timer name -> index of its assignment
        for i, stmt in enumerate(body):
            if isinstance(stmt, ast.Assign) and _is_clock_call(stmt.value, timp):
                for t in stmt.targets:
                    for name in _target_names(t):
                        starts[name] = i
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are scanned as their own bodies
            for node in _walk_skip_defs(stmt):
                if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)):
                    continue
                right = node.right
                if not (isinstance(right, ast.Name) and right.id in starts):
                    continue
                left_ok = _is_clock_call(node.left, timp) or (
                    isinstance(node.left, ast.Name) and node.left.id in starts
                )
                if not left_ok:
                    continue
                region = body[starts[right.id] + 1 : i + 1]
                if _calls_jitted(region, jitted) and not _has_sync(region):
                    yield self.violation(
                        module, node,
                        f"`{right.id}` times a region that calls a jitted "
                        "function but never syncs — jax dispatch is async, so "
                        "this measures enqueue, not compute; add "
                        "jax.block_until_ready(...) (or a scalar fetch) "
                        "before the closing clock read",
                    )
                # one report per timed region: a reused start (display,
                # logging) must not re-flag the same measurement
                starts.pop(right.id, None)


# -- sync-transfer-in-loop --------------------------------------------------

#: numpy entry points that materialize a device array on the host
_TRANSFER_NP_FUNCS = {"asarray", "array"}
#: jax entry points that move data across the host/device boundary
_TRANSFER_JAX_FUNCS = {"device_get", "device_put"}


def _base_name(node: ast.AST) -> Optional[str]:
    """The root Name of a ``x`` / ``x[1]`` / ``x.attr[0]`` chain."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _transfer_target(node: ast.AST, imp: _Imports) -> Optional[str]:
    """If ``node`` is a blocking host/device transfer call, return the
    base name of the array it syncs on; else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "block_until_ready" and not node.args:
        return _base_name(f.value)
    if not node.args:
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id in imp.numpy and f.attr in _TRANSFER_NP_FUNCS:
            return _base_name(node.args[0])
        if f.value.id in imp.jax and f.attr in _TRANSFER_JAX_FUNCS:
            return _base_name(node.args[0])
    return None


def _dispatch_targets(stmt: ast.stmt, imp: _Imports) -> List[str]:
    """Names this statement binds directly from a (possibly async) device
    dispatch: ``x = some_call(...)`` where the call is not a numpy/host
    builtin. The loop-carried proxy for 'work was dispatched this
    iteration'."""
    if not isinstance(stmt, ast.Assign) or not isinstance(stmt.value, ast.Call):
        return []
    f = stmt.value.func
    if isinstance(f, ast.Name) and f.id in _STATIC_CALLS:
        return []
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id in imp.numpy
    ):
        return []
    return [n for t in stmt.targets for n in _target_names(t)]


class SyncTransferInLoopChecker(Checker):
    rule = "sync-transfer-in-loop"
    doc = (
        "np.asarray/device_get/device_put/block_until_ready on a value "
        "dispatched earlier in the same loop iteration — the transfer "
        "serializes host and device every iteration; dispatch the next "
        "iteration's work before blocking (double-buffer / overlap seam)."
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        imp = _module_imports(module)
        if not (imp.numpy or imp.jax):
            return
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            dispatched: Dict[str, bool] = {}
            yield from self._scan(module, imp, [*loop.body, *loop.orelse], dispatched)

    def _scan(
        self,
        module: LintModule,
        imp: _Imports,
        body: Sequence[ast.stmt],
        dispatched: Dict[str, bool],
    ) -> Iterator[Violation]:
        for stmt in body:
            # nested defs run on their own schedule; nested loops are
            # scanned as their own loops (one report per pattern)
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.For, ast.While)
            ):
                continue
            if isinstance(stmt, (ast.If, ast.With, ast.Try)):
                yield from self._scan_exprs(module, imp, _stmt_exprs(stmt), dispatched)
                if isinstance(stmt, ast.If):
                    sub = [stmt.body, stmt.orelse]
                elif isinstance(stmt, ast.With):
                    sub = [stmt.body]
                else:
                    sub = [
                        stmt.body,
                        *[h.body for h in stmt.handlers],
                        stmt.orelse,
                        stmt.finalbody,
                    ]
                for b in sub:
                    yield from self._scan(module, imp, b, dispatched)
                continue
            yield from self._scan_exprs(module, imp, [stmt], dispatched)
            for name in _dispatch_targets(stmt, imp):
                dispatched[name] = True

    def _scan_exprs(
        self,
        module: LintModule,
        imp: _Imports,
        roots: Sequence[Optional[ast.AST]],
        dispatched: Dict[str, bool],
    ) -> Iterator[Violation]:
        for root in roots:
            if root is None:
                continue
            for node in _walk_skip_defs(root):
                name = _transfer_target(node, imp)
                if name is not None and dispatched.pop(name, False):
                    yield self.violation(
                        module, node,
                        f"`{name}` was dispatched earlier in this loop "
                        "iteration and is synced here — host and device run "
                        "serially, every iteration; dispatch iteration i+1's "
                        "work before blocking on i (double-buffer), or hoist "
                        "the transfer out of the loop",
                    )


CHECKERS = [
    TracedBranchChecker(),
    NumpyInJitChecker(),
    StaticArgsChecker(),
    JitInLoopChecker(),
    ImplicitDtypeChecker(),
    UnsyncedTimingChecker(),
    SyncTransferInLoopChecker(),
]
