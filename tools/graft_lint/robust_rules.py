"""Robustness checkers.

The fault-tolerance layer (:mod:`raft_tpu.robust`) only works if
failures are *visible*: injected faults must surface as typed errors,
fallbacks must be counted, retries must be logged. The one pattern that
defeats all of it is the silently swallowed exception:

* ``silent-except`` — an ``except`` handler whose body is only
  ``pass`` (or ``...``). The failure disappears: no re-raise, no obs
  counter, no degraded-mode marker. Handle it, count it
  (``obs.inc(...)``), or at minimum leave a comment and a
  ``# graft-lint: ignore[silent-except]`` where a human judged the
  drop safe (e.g. best-effort cache cleanup).
"""
from __future__ import annotations

import ast
from typing import Iterator

from tools.graft_lint.core import Checker, LintModule, Violation


def _is_noop(stmt: ast.stmt) -> bool:
    if isinstance(stmt, ast.Pass):
        return True
    # bare `...` as a statement
    return (
        isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, ast.Constant)
        and stmt.value.value is Ellipsis
    )


class SilentExceptChecker(Checker):
    rule = "silent-except"
    doc = (
        "except handler whose body is only pass/... — the failure is "
        "swallowed with no re-raise, log, or obs counter"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not all(_is_noop(s) for s in node.body):
                continue
            if isinstance(node.type, ast.Name):
                caught = node.type.id
            elif node.type is None:
                caught = "everything"
            else:
                caught = ast.unparse(node.type)
            yield self.violation(
                module, node,
                f"except block silently swallows {caught} — re-raise, "
                "count it via obs.inc(), or suppress with a justifying "
                "comment",
            )


CHECKERS = [SilentExceptChecker()]
