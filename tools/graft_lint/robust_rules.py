"""Robustness checkers.

The fault-tolerance layer (:mod:`raft_tpu.robust`) only works if
failures are *visible*: injected faults must surface as typed errors,
fallbacks must be counted, retries must be logged. The one pattern that
defeats all of it is the silently swallowed exception:

* ``silent-except`` — an ``except`` handler whose body is only
  ``pass`` (or ``...``). The failure disappears: no re-raise, no obs
  counter, no degraded-mode marker. Handle it, count it
  (``obs.inc(...)``), or at minimum leave a comment and a
  ``# graft-lint: ignore[silent-except]`` where a human judged the
  drop safe (e.g. best-effort cache cleanup).

* ``non-atomic-write`` — ``open(path, "w"/"wb")`` straight onto a
  persisted artifact path. A crash (or fault injection) mid-write
  leaves a torn file that a later reader sees as corruption; the
  serialization layer's contract is temp + fsync + ``os.replace``
  (:func:`raft_tpu.core.serialize.atomic_write`), under which a
  half-written file can never be observed at the published path.
  Writes whose target is visibly a temp name, or that sit in a
  function that also renames (``os.replace``/``os.rename``) or calls
  ``atomic_write``, are recognized as the idiom itself and not
  flagged; transient debug/scratch output gets a rationale'd
  ``# graft-lint: ignore[non-atomic-write]``.

* ``blocking-under-lock`` — an index build, artifact write, sleep, or
  device sync dispatched while a ``threading.Lock``/mutex context is
  held. Every writer and searcher contending on that lock waits out
  the whole operation — the p99 becomes the rebuild time (the exact
  bug background compaction removes: pin under the lock, rebuild
  outside it, re-enter briefly for the flip). The check is
  interprocedural: blocking primitives (``build``/``fsync``/
  ``rmtree``/``sleep``/… — :data:`tools.graft_lint.core.
  BLOCKING_PRIMITIVES`) are propagated over the project call graph, so
  a call that *reaches* an fsync three frames down is flagged at the
  call site under the lock. Locks resolved against
  ``lock_order.toml`` get contract-aware treatment: a ``may_block``
  lock (the compaction mutex serializes whole rebuilds by design)
  exempts its body, and ``[[allow_blocking]]`` entries excuse one
  callee path under one lock (the durable-then-visible WAL fsync).
  Lock-like ``with`` s the manifest does not know fall back to the
  lexical direct-call check; residual deliberate sites carry a
  rationale'd ``# graft-lint: ignore[blocking-under-lock]``.

* ``unbounded-queue`` — a work-queue construction with no bound:
  ``queue.Queue()`` / ``LifoQueue()`` / ``PriorityQueue()`` without a
  positive ``maxsize``, ``queue.SimpleQueue()`` (unboundable by
  design), or ``collections.deque()`` without ``maxlen``. An unbounded
  queue turns overload into unbounded latency and OOM instead of the
  typed backpressure the serving layer promises
  (:class:`raft_tpu.serve.QueueFull`); bound it, or suppress with a
  ``# graft-lint: ignore[unbounded-queue]`` where the producer is
  provably bounded (e.g. a fixed-size scratch deque).
"""
from __future__ import annotations

import ast
from typing import Iterator

from tools.graft_lint import lockmanifest
from tools.graft_lint.concurrency_rules import resolve_lock
from tools.graft_lint.core import (
    BLOCKING_PRIMITIVES,
    Checker,
    LintModule,
    Violation,
    walk_executed,
)


def _is_noop(stmt: ast.stmt) -> bool:
    if isinstance(stmt, ast.Pass):
        return True
    # bare `...` as a statement
    return (
        isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, ast.Constant)
        and stmt.value.value is Ellipsis
    )


class SilentExceptChecker(Checker):
    rule = "silent-except"
    doc = (
        "except handler whose body is only pass/... — the failure is "
        "swallowed with no re-raise, log, or obs counter"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not all(_is_noop(s) for s in node.body):
                continue
            if isinstance(node.type, ast.Name):
                caught = node.type.id
            elif node.type is None:
                caught = "everything"
            else:
                caught = ast.unparse(node.type)
            yield self.violation(
                module, node,
                f"except block silently swallows {caught} — re-raise, "
                "count it via obs.inc(), or suppress with a justifying "
                "comment",
            )


#: constructors from the ``queue`` module that accept a ``maxsize`` bound
_BOUNDABLE_QUEUES = ("Queue", "LifoQueue", "PriorityQueue")


def _call_name(node: ast.Call):
    """(module_hint, name) for ``Name(...)`` / ``module.Name(...)``
    calls; (None, None) for anything fancier (method results, lambdas —
    stay silent on what we can't identify)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return None, fn.id
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        return fn.value.id, fn.attr
    return None, None


def _is_nonpositive_literal(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        if node.value is None:
            return True
        if isinstance(node.value, (int, float)) and not isinstance(node.value, bool):
            return node.value <= 0
    return False


class UnboundedQueueChecker(Checker):
    rule = "unbounded-queue"
    doc = (
        "queue.Queue()/deque() work queue constructed without a bound "
        "(maxsize/maxlen) — overload becomes unbounded latency and OOM "
        "instead of typed backpressure"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            mod, name = _call_name(node)
            if name is None:
                continue
            if name == "SimpleQueue" and mod in (None, "queue"):
                # no maxsize parameter exists: unboundable by design
                yield self.violation(
                    module, node,
                    "queue.SimpleQueue() cannot be bounded — use "
                    "queue.Queue(maxsize=...) so overload is rejected, "
                    "not accumulated",
                )
                continue
            if name in _BOUNDABLE_QUEUES and mod in (None, "queue"):
                bound = node.args[0] if node.args else None
                for kw in node.keywords:
                    if kw.arg == "maxsize":
                        bound = kw.value
                if bound is None or _is_nonpositive_literal(bound):
                    yield self.violation(
                        module, node,
                        f"{name}() has no maxsize bound (<=0 means "
                        "unbounded) — pass a positive maxsize so a full "
                        "queue rejects instead of growing",
                    )
                continue
            if name == "deque" and mod in (None, "collections"):
                # deque(iterable, maxlen) — second positional is the bound
                bound = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg == "maxlen":
                        bound = kw.value
                if bound is None or _is_nonpositive_literal(bound):
                    yield self.violation(
                        module, node,
                        "deque() has no maxlen bound — a deque used as a "
                        "work queue must carry maxlen (and the producer "
                        "must reject before append: maxlen alone drops "
                        "silently)",
                    )


#: names whose presence in the enclosing function marks the atomic
#: temp-then-rename idiom (the open() is the temp leg, not the publish)
_ATOMIC_MARKERS = ("replace", "rename", "atomic_write")


def _write_mode(node: ast.Call):
    """The string literal mode of an ``open()`` call when it is a plain
    write ("w"/"wb", any +/encoding flags), else None."""
    mode = node.args[1] if len(node.args) >= 2 else None
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return None
    return mode.value if mode.value.startswith("w") else None


def _mentions_temp(expr: ast.expr) -> bool:
    text = ast.unparse(expr).lower()
    return "tmp" in text or "temp" in text


class NonAtomicWriteChecker(Checker):
    rule = "non-atomic-write"
    doc = (
        'open(path, "w"/"wb") straight onto a persisted path — a crash '
        "mid-write publishes a torn file; use the temp-fsync-rename "
        "idiom (core.serialize.atomic_write)"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        # map each node to its nearest enclosing function so the
        # atomic-idiom scan stays local (a rename elsewhere in the
        # module must not excuse an unrelated write)
        scope_of = {}
        for fn in ast.walk(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in ast.walk(fn):
                    scope_of[child] = fn  # innermost wins: walk order is outer-first,
                # so later (inner) functions overwrite their children's entries
        atomic_scopes = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                _, name = _call_name(node)
                if name in _ATOMIC_MARKERS:
                    atomic_scopes.add(id(scope_of.get(node)))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            mod, name = _call_name(node)
            if name != "open" or mod not in (None, "io"):
                continue
            mode = _write_mode(node)
            if mode is None or not node.args:
                continue
            if _mentions_temp(node.args[0]):
                continue  # the temp leg of the idiom
            if id(scope_of.get(node)) in atomic_scopes:
                continue  # enclosing function renames/atomic-writes
            yield self.violation(
                module, node,
                f'open(..., "{mode}") writes the published path directly '
                "— a crash mid-write leaves a torn artifact; write a temp "
                "file, fsync, then os.replace (see "
                "core.serialize.atomic_write), or suppress with a "
                "rationale for transient output",
            )


#: substrings of a ``with`` context-expression name that mark it as a
#: lock acquisition (``self._lock``, ``mut._compact_mutex``, …)
_LOCK_HINTS = ("lock", "mutex")

#: the direct blocking seeds now live in core (the call graph
#: propagates them); this alias keeps the lexical fallback in sync
_BLOCKING_NAMES = BLOCKING_PRIMITIVES


def _last_component(expr):
    """The rightmost name of an expression: ``a.b.c()`` -> "c",
    ``lock`` -> "lock"; None for anything unnameable."""
    while isinstance(expr, ast.Call):
        expr = expr.func
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_lock_expr(expr) -> bool:
    name = _last_component(expr)
    return name is not None and any(h in name.lower() for h in _LOCK_HINTS)


def _walk_executed(stmts):
    """Walk statements without descending into nested def/lambda bodies
    — deferred code does not run while the lock is held."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class BlockingUnderLockChecker(Checker):
    rule = "blocking-under-lock"
    doc = (
        "index build / artifact write / device sync reachable (through "
        "calls) while a held threading lock is held — writers and "
        "searchers queue behind the whole operation; pin under the "
        "lock, do the work outside, re-enter for the flip"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        project = getattr(module, "project", None)
        manifest = lockmanifest.load_manifest()
        blocking = project.blocking_facts() if project is not None else {}
        # map executed nodes to their enclosing indexed function so
        # with-contexts and calls can use receiver-type resolution
        owner = {}
        if project is not None:
            for info in project.functions.values():
                if info.module is not module:
                    continue
                for n in walk_executed(info.node.body):
                    owner[id(n)] = info
        flagged = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lockish = [
                item for item in node.items
                if _is_lock_expr(item.context_expr)
            ]
            if not lockish:
                continue
            info = owner.get(id(node))
            decls, unresolved = [], False
            if manifest is not None:
                for item in lockish:
                    d = resolve_lock(project, manifest, module, info, item.context_expr)
                    if d is None:
                        unresolved = True
                    else:
                        decls.append(d)
            else:
                unresolved = True
            if decls and not unresolved and all(d.may_block for d in decls):
                # holders of this lock are expected to block (e.g. the
                # compaction mutex serializes whole rebuilds); inner
                # locks are judged at their own `with`
                continue
            judge = [d for d in decls if not d.may_block]
            lexical_only = unresolved or not judge or project is None
            for child in _walk_executed(node.body):
                if not isinstance(child, ast.Call) or id(child) in flagged:
                    continue
                name = _last_component(child.func)
                direct_hit = name in _BLOCKING_NAMES
                target = None
                if project is not None and info is not None:
                    target = project.resolve_call(info, child)
                if lexical_only:
                    if direct_hit:
                        flagged.add(id(child))
                        yield self.violation(
                            module, child,
                            f"{name}() runs while a lock is held — writers "
                            "and searchers queue behind it for the whole "
                            "call; pin state under the lock, run the "
                            "blocking work outside it, and re-enter only "
                            "for the pointer flip (see "
                            "raft_tpu.mutable.maintenance), or suppress "
                            "with a rationale where blocking is the "
                            "documented contract",
                        )
                    continue
                for d in judge:
                    if direct_hit:
                        chain = [target] if target else []
                        if manifest.allows_blocking(d.name, chain, name):
                            continue
                        flagged.add(id(child))
                        yield self.violation(
                            module, child,
                            f"{name}() blocks while {d.name} is held — "
                            "everyone contending on it waits out the call; "
                            "move it outside the critical section, or add "
                            "an [[allow_blocking]] entry to lock_order."
                            "toml / an inline rationale where blocking is "
                            "the contract",
                        )
                        break
                    if target is None:
                        continue
                    hit = None
                    for (container, prim), (_ln, path) in blocking.get(
                        target, {}
                    ).items():
                        chain = [target] + path
                        if not chain or chain[-1] != container:
                            chain.append(container)
                        if not manifest.allows_blocking(d.name, chain, prim):
                            hit = (prim, chain)
                            break
                    if hit is not None:
                        prim, chain = hit
                        flagged.add(id(child))
                        yield self.violation(
                            module, child,
                            f"{name}() reaches {prim}() (via "
                            f"{' -> '.join(chain)}) while {d.name} is held "
                            "— the critical section blocks for the whole "
                            "downstream operation; restructure, or excuse "
                            "this path with an [[allow_blocking]] entry in "
                            "lock_order.toml",
                        )
                        break


CHECKERS = [
    SilentExceptChecker(),
    UnboundedQueueChecker(),
    NonAtomicWriteChecker(),
    BlockingUnderLockChecker(),
]
