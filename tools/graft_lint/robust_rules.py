"""Robustness checkers.

The fault-tolerance layer (:mod:`raft_tpu.robust`) only works if
failures are *visible*: injected faults must surface as typed errors,
fallbacks must be counted, retries must be logged. The one pattern that
defeats all of it is the silently swallowed exception:

* ``silent-except`` — an ``except`` handler whose body is only
  ``pass`` (or ``...``). The failure disappears: no re-raise, no obs
  counter, no degraded-mode marker. Handle it, count it
  (``obs.inc(...)``), or at minimum leave a comment and a
  ``# graft-lint: ignore[silent-except]`` where a human judged the
  drop safe (e.g. best-effort cache cleanup).

* ``unbounded-queue`` — a work-queue construction with no bound:
  ``queue.Queue()`` / ``LifoQueue()`` / ``PriorityQueue()`` without a
  positive ``maxsize``, ``queue.SimpleQueue()`` (unboundable by
  design), or ``collections.deque()`` without ``maxlen``. An unbounded
  queue turns overload into unbounded latency and OOM instead of the
  typed backpressure the serving layer promises
  (:class:`raft_tpu.serve.QueueFull`); bound it, or suppress with a
  ``# graft-lint: ignore[unbounded-queue]`` where the producer is
  provably bounded (e.g. a fixed-size scratch deque).
"""
from __future__ import annotations

import ast
from typing import Iterator

from tools.graft_lint.core import Checker, LintModule, Violation


def _is_noop(stmt: ast.stmt) -> bool:
    if isinstance(stmt, ast.Pass):
        return True
    # bare `...` as a statement
    return (
        isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, ast.Constant)
        and stmt.value.value is Ellipsis
    )


class SilentExceptChecker(Checker):
    rule = "silent-except"
    doc = (
        "except handler whose body is only pass/... — the failure is "
        "swallowed with no re-raise, log, or obs counter"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not all(_is_noop(s) for s in node.body):
                continue
            if isinstance(node.type, ast.Name):
                caught = node.type.id
            elif node.type is None:
                caught = "everything"
            else:
                caught = ast.unparse(node.type)
            yield self.violation(
                module, node,
                f"except block silently swallows {caught} — re-raise, "
                "count it via obs.inc(), or suppress with a justifying "
                "comment",
            )


#: constructors from the ``queue`` module that accept a ``maxsize`` bound
_BOUNDABLE_QUEUES = ("Queue", "LifoQueue", "PriorityQueue")


def _call_name(node: ast.Call):
    """(module_hint, name) for ``Name(...)`` / ``module.Name(...)``
    calls; (None, None) for anything fancier (method results, lambdas —
    stay silent on what we can't identify)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return None, fn.id
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        return fn.value.id, fn.attr
    return None, None


def _is_nonpositive_literal(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        if node.value is None:
            return True
        if isinstance(node.value, (int, float)) and not isinstance(node.value, bool):
            return node.value <= 0
    return False


class UnboundedQueueChecker(Checker):
    rule = "unbounded-queue"
    doc = (
        "queue.Queue()/deque() work queue constructed without a bound "
        "(maxsize/maxlen) — overload becomes unbounded latency and OOM "
        "instead of typed backpressure"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            mod, name = _call_name(node)
            if name is None:
                continue
            if name == "SimpleQueue" and mod in (None, "queue"):
                # no maxsize parameter exists: unboundable by design
                yield self.violation(
                    module, node,
                    "queue.SimpleQueue() cannot be bounded — use "
                    "queue.Queue(maxsize=...) so overload is rejected, "
                    "not accumulated",
                )
                continue
            if name in _BOUNDABLE_QUEUES and mod in (None, "queue"):
                bound = node.args[0] if node.args else None
                for kw in node.keywords:
                    if kw.arg == "maxsize":
                        bound = kw.value
                if bound is None or _is_nonpositive_literal(bound):
                    yield self.violation(
                        module, node,
                        f"{name}() has no maxsize bound (<=0 means "
                        "unbounded) — pass a positive maxsize so a full "
                        "queue rejects instead of growing",
                    )
                continue
            if name == "deque" and mod in (None, "collections"):
                # deque(iterable, maxlen) — second positional is the bound
                bound = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg == "maxlen":
                        bound = kw.value
                if bound is None or _is_nonpositive_literal(bound):
                    yield self.violation(
                        module, node,
                        "deque() has no maxlen bound — a deque used as a "
                        "work queue must carry maxlen (and the producer "
                        "must reject before append: maxlen alone drops "
                        "silently)",
                    )


CHECKERS = [SilentExceptChecker(), UnboundedQueueChecker()]
