"""Dispatch-policy checkers: "auto" resolution must route through the
planner.

The repo's dispatch decisions ("auto" search/merge/comm/delta modes)
resolve through one costed chokepoint — ``raft_tpu.plan`` — so every
policy is explainable from a single cost table instead of re-derived by
scattered one-liner heuristics that drift apart:

* ``scattered-auto`` — an ``== "auto"`` / ``!= "auto"`` string-literal
  comparison inside a function with no reference to the planner is a
  local dispatch heuristic growing outside the chokepoint. Route the
  branch through a ``raft_tpu.plan`` resolver (gate-off legacy branches
  in the same function are fine — the planner reference marks the
  function as routed), or carry a rationale'd inline suppression.

Membership validations (``mode in ("auto", ...)``) are not flagged —
an allowlist check is input validation, not dispatch. Only equality
comparisons against the literal decide a branch.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from tools.graft_lint.core import Checker, LintModule, Violation

#: attribute/name spellings that mark a function as planner-routed
_PLAN_PREFIXES = ("plan_", "_plan", "planned_")


def _is_plan_ref(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        nid = node.id
        return nid == "plan" or nid.endswith("_plan") or nid.startswith(_PLAN_PREFIXES)
    if isinstance(node, ast.Attribute):
        attr = node.attr
        return attr == "plan" or attr.startswith(_PLAN_PREFIXES)
    if isinstance(node, ast.ImportFrom):
        mod = node.module or ""
        if mod == "raft_tpu.plan" or mod.startswith("raft_tpu.plan."):
            return True
        return mod == "raft_tpu" and any(a.name == "plan" for a in node.names)
    return False


def _routes_through_planner(fn: ast.AST) -> bool:
    return any(_is_plan_ref(n) for n in ast.walk(fn))


def _auto_compares(fn: ast.AST, nested: List[ast.AST]) -> Iterator[ast.Compare]:
    """Eq/NotEq comparisons against the literal "auto" directly in
    ``fn`` (not inside one of its ``nested`` function definitions —
    those are scoped to the nested function's own walk)."""
    skip = set()
    for sub in nested:
        skip.update(id(n) for n in ast.walk(sub))
        skip.discard(id(sub))
    for node in ast.walk(fn):
        if id(node) in skip or not isinstance(node, ast.Compare):
            continue
        sides = [node.left, *node.comparators]
        literal = any(
            isinstance(s, ast.Constant) and s.value == "auto" for s in sides  # graft-lint: ignore[scattered-auto] — the detector's own matching literal, not a dispatch branch
        )
        if literal and all(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            yield node


class ScatteredAutoChecker(Checker):
    rule = "scattered-auto"
    doc = (
        'string-literal "auto" dispatch branch in a function that never '
        "references the planner — resolve the decision through a "
        "raft_tpu.plan resolver so every policy prices from one cost "
        "table instead of a drifting local heuristic"
    )

    def check(self, module: LintModule) -> Iterator[Violation]:
        funcs = [
            n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for fn in funcs:
            nested = [
                n for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            ]
            cmps = list(_auto_compares(fn, nested))
            if not cmps:
                continue
            # the function (or any function nesting it) routing through
            # the planner clears its whole subtree: a gate-off legacy
            # branch next to the planner call is the sanctioned pattern
            if any(
                _routes_through_planner(f)
                for f in funcs
                if f is fn or any(n is fn for n in ast.walk(f))
            ):
                continue
            for cmp_node in cmps:
                yield self.violation(
                    module, cmp_node,
                    '"auto" resolved by a local heuristic in '
                    f"{fn.name}() — route the decision through a "
                    "raft_tpu.plan resolver (plan_search_mode, "
                    "plan_merge_mode, ...) so the choice is costed and "
                    "explainable, or suppress with a rationale",
                )


CHECKERS = [ScatteredAutoChecker()]
